"""Build/packaging: compiles the native core (reference analog:
``setup.py`` CMake superbuild — plain make here) and installs the ``hvdrun``
console script (reference: ``setup.py:199``)."""

import os
import subprocess

from setuptools import setup, find_packages
from setuptools.command.build_ext import build_ext
from setuptools.command.build_py import build_py


def _make_core() -> None:
    cpp = os.path.join(os.path.dirname(os.path.abspath(__file__)), "cpp")
    if os.path.isdir(cpp):
        subprocess.run(["make", "-j4"], cwd=cpp, check=True)


class BuildWithCore(build_py):
    def run(self):
        _make_core()
        super().run()


class BuildCoreExt(build_ext):
    """`python setup.py build_ext` — the command the runtime's
    missing-library error advertises — must actually build the core."""

    def run(self):
        _make_core()
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description="TPU-native distributed training framework "
                "(Horovod-class capabilities on JAX/XLA)",
    packages=find_packages(include=["horovod_tpu*"]),
    package_data={"horovod_tpu.core": ["libhvdcore.so"]},
    cmdclass={"build_py": BuildWithCore, "build_ext": BuildCoreExt},
    entry_points={
        "console_scripts": [
            "hvdrun = horovod_tpu.runner.launch:main",
            "horovodrun_tpu = horovod_tpu.runner.launch:main",
        ]
    },
    python_requires=">=3.10",
)
