"""Serving latency bench: closed-loop load against a local replica pair.

Emits one ``BENCH_SERVE``-prefixed JSON line (and optionally a file) —
the serving analog of the training bench's artifact contract
(ci/check_bench.py ``--serving`` gates it): qps, windowed p50/p99,
shed fraction, and the zero-drop audit.  A "clean" p99 that was bought
by shedding requests is NOT clean — the artifact carries
``shed_fraction`` precisely so the gate can refuse it.

Default shape: ``--replicas 2`` replica PROCESSES (the fleet heals and
swaps exactly as in production) driven by ``--clients`` closed-loop
threads for ``--duration`` seconds.  ``--in-process`` swaps the
subprocess fleet for two in-process replicas (faster start; used by the
bench contract tests).

Run:  python benchmarks/serving_bench.py --duration 5 --out BENCH_SERVE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run_bench(replicas: int = 2, clients: int = 4, duration_s: float = 5.0,
              dim: int = 16, in_process: bool = False,
              warmup_s: float = 1.0) -> dict:
    from horovod_tpu.serving import ReplicaFleet, ReplicaServer, Router
    from horovod_tpu.serving.batcher import SheddedError

    servers = []
    fleet = None
    if in_process:
        servers = [ReplicaServer(dim=dim, replica_id=f"bench{i}").start()
                   for i in range(replicas)]
        endpoints = [("127.0.0.1", s.port) for s in servers]
        get_endpoints = lambda: endpoints  # noqa: E731
    else:
        fleet = ReplicaFleet(size=replicas, dim=dim).start(
            ready_timeout_s=120.0)
        get_endpoints = fleet.endpoints
    router = Router(get_endpoints)

    stop = threading.Event()
    t_measure_start = [0.0]
    counts = {"ok": 0, "shed": 0, "failed": 0}
    counts_lock = threading.Lock()
    latencies: list = []
    traced: list = []  # (latency, trace_id) per measured ok request
    stage_sums: dict = {}  # ledger stage -> total seconds (measured oks)

    def client(i: int) -> None:
        n = 0
        x = [float(i)] * dim
        while not stop.is_set():
            n += 1
            t0 = time.monotonic()
            try:
                doc = router.submit(x, req_id=f"bench-c{i}-{n}")
                outcome = "ok"
            except SheddedError:
                outcome = "shed"
            except Exception:
                outcome = "failed"
            dt = time.monotonic() - t0
            if t_measure_start[0] and t0 >= t_measure_start[0]:
                with counts_lock:
                    counts[outcome] += 1
                    if outcome == "ok":
                        latencies.append(dt)
                        if doc.get("trace"):
                            traced.append((dt, doc["trace"]))
                        st = doc.get("stages")
                        if isinstance(st, dict):
                            for k, v in st.items():
                                if isinstance(v, (int, float)):
                                    stage_sums[k] = \
                                        stage_sums.get(k, 0.0) + float(v)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(warmup_s)  # absorb compile + connection setup
    t_measure_start[0] = time.monotonic()
    time.sleep(duration_s)
    stop.set()
    # join budget must COVER a request's full retry deadline: cutting
    # a legitimately in-flight submit off mid-retry would record a
    # false unanswered=1 and fail the zero-drop gate for a run that
    # dropped nothing
    join_deadline = time.monotonic() + router.default_deadline_s + 5.0
    for t in threads:
        t.join(timeout=max(join_deadline - time.monotonic(), 0.1))
    measured_s = time.monotonic() - t_measure_start[0]
    router.close()
    acct = router.accounting()
    if fleet is not None:
        fleet.stop()
    for s in servers:
        s.stop()

    latencies.sort()
    from horovod_tpu.serving.metrics import percentile

    def pct(q: float) -> float:
        return percentile(latencies, q)

    # the slowest-request trace (docs/OBSERVABILITY.md "Causal
    # tracing"): the per-hop breakdown of the request CLOSEST TO the
    # p99 — the artifact answers "where did the tail latency go", not
    # just "how big is it".  Hops come from this process's flight ring
    # (router spans always; replica spans too under --in-process —
    # subprocess replicas keep theirs in their own rings).
    slowest = None
    if traced:
        p99 = pct(0.99)
        lat, trace_id = min(traced, key=lambda t: abs(t[0] - p99))
        try:
            from horovod_tpu import tracing  # noqa: F401
            from horovod_tpu.diagnostics.flight_recorder import recorder
            from horovod_tpu.tracing.reader import spans_from_events
            spans, _pts = spans_from_events(recorder().events(),
                                            trace_id=trace_id)
            slowest = {
                "trace": trace_id,
                "latency_s": round(lat, 6),
                "hops": [{"plane": s["plane"], "name": s["name"],
                          "dur_s": s["dur_s"],
                          **{k: s["attrs"][k]
                             for k in ("target", "replica", "code")
                             if s["attrs"].get(k) is not None}}
                         for s in sorted(spans,
                                         key=lambda s: s["start"])],
            }
        except Exception:
            slowest = {"trace": trace_id, "latency_s": round(lat, 6),
                       "hops": []}

    # the request ledger's view of the run (docs/OBSERVABILITY.md
    # "Serving request ledger"): per-stage totals across every measured
    # ok, their shares, and the books-close check — check_bench
    # --serving refuses an artifact whose unattributed residual says
    # the decomposition no longer explains the latency it reports
    from horovod_tpu.serving import ledger
    stage_total = sum(stage_sums.values())
    stage_doc = {
        "stage_seconds": {k: round(v, 6)
                          for k, v in sorted(stage_sums.items())},
        "stage_shares": {k: round(v / stage_total, 4)
                         for k, v in sorted(stage_sums.items())}
        if stage_total > 0 else {},
        "stage_unattributed_frac": round(
            stage_sums.get(ledger.RESIDUAL, 0.0) / stage_total, 6)
        if stage_total > 0 else None,
        "dominant_stage": ledger.dominant_stage(stage_sums),
    }
    # a bounded latency sample (strided over the sorted list, endpoints
    # kept) so the gate can REPLAY the percentile math with the shared
    # quantile implementation instead of trusting the number
    sample = latencies
    if len(sample) > 512:
        stride = len(sample) / 511.0
        sample = [latencies[min(int(i * stride), len(latencies) - 1)]
                  for i in range(511)] + [latencies[-1]]

    from horovod_tpu.tracing import enabled as tracing_enabled
    total = sum(counts.values())
    return {
        "tracing_enabled": bool(tracing_enabled()),
        "slowest_request_trace": slowest,
        "bench": "serving",
        "replicas": replicas,
        "clients": clients,
        "dim": dim,
        "in_process": bool(in_process),
        "duration_s": round(measured_s, 3),
        "requests": total,
        "qps": round(counts["ok"] / max(measured_s, 1e-9), 2),
        "p50_s": round(pct(0.50), 6),
        "p99_s": round(pct(0.99), 6),
        "latency_sample": [round(v, 6) for v in sample],
        **stage_doc,
        "shed_fraction": round(counts["shed"] / total, 6) if total else 0.0,
        "failed": counts["failed"],
        "unanswered": len(acct["unanswered"]),
        "answered_twice": len(acct["answered_twice"]),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


def run_gen_bench(requests: int = 24, n_slots: int = 4,
                  prefill_chunk: int = 8, max_prompt: int = 24,
                  max_new_lo: int = 4, max_new_hi: int = 16,
                  page_bytes: int = 4096, seed: int = 0) -> dict:
    """Generate-mode bench: overlapping mixed-length prompt streams
    through the continuous token-level engine, then the SAME request
    set through the request-level gang baseline on the SAME warm
    compiled step functions — the artifact carries both so the
    ``--serving-gen`` gate can refuse a continuous engine that stopped
    beating request-granular batching (``speedup``), alongside
    tokens/s, TTFT/ITL percentiles, slot occupancy, page-pool
    high-water, and the one-compile guarantee (``decode_compiles``)."""
    import numpy as np

    from horovod_tpu import tracing
    from horovod_tpu.profiling import compile_watch
    from horovod_tpu.serving.generate import (GenerateEngine,
                                              demo_gen_setup,
                                              request_level_generate)
    from horovod_tpu.serving.metrics import percentile

    compile_watch.ensure_installed()
    compile_watch.reset_counts()
    params, cfg = demo_gen_setup()
    # a small page budget on the tiny demo model so the bench actually
    # exercises multi-page tables, not one page per slot
    engine = GenerateEngine(params, cfg, n_slots=n_slots,
                            prefill_chunk=prefill_chunk,
                            page_bytes=page_bytes)
    rng = np.random.RandomState(seed)
    reqset = [
        (rng.randint(1, cfg.vocab_size,
                     size=int(rng.randint(1, max_prompt + 1))),
         int(rng.randint(max_new_lo, max_new_hi + 1)))
        for _ in range(requests)
    ]

    # warmup: pay both compiles outside the measured WINDOW but inside
    # the compile COUNT — decode_compiles must end the whole bench
    # (warmup + continuous churn + gang baseline) at exactly 1
    warm = engine.submit("warmup", [1, 2, 3], 2)
    while warm.state != "done":
        engine.step_once()

    # continuous run: all streams overlap, token-level batching
    emit_times: dict = {i: [] for i in range(requests)}
    reqs = []
    for i, (prompt, max_new) in enumerate(reqset):
        def on_token(_tok, _i=i):
            emit_times[_i].append(time.monotonic())
        reqs.append(engine.submit(
            f"gen-{i}", prompt, max_new,
            trace=tracing.new_trace("serving"), on_token=on_token))
    steps0, chunks0 = engine.decode_steps_total, engine.prefill_chunks_total
    occupancy: list = []
    t0 = time.monotonic()
    while any(r.state != "done" for r in reqs):
        engine.step_once()
        occupancy.append(engine.scheduler.occupied() / n_slots)
    cont_s = max(time.monotonic() - t0, 1e-9)
    cont_steps = engine.decode_steps_total - steps0
    cont_chunks = engine.prefill_chunks_total - chunks0
    total_tokens = sum(len(r.tokens) for r in reqs)
    failed = sum(1 for r in reqs if r.finish_reason != "length")

    ttfts = sorted(r.first_token_at - r.submitted_at
                   for r in reqs if r.first_token_at)
    itls = sorted(b - a for times in emit_times.values()
                  for a, b in zip(times, times[1:]))

    # baseline: same requests, gang-scheduled at request granularity
    # through the same warm engine (early finishers strand their slot),
    # with the SAME per-request tracing/callback instrumentation so the
    # comparison charges identical overhead to both sides
    base_times: dict = {i: [] for i in range(requests)}
    t0 = time.monotonic()
    base_reqs = request_level_generate(
        engine, reqset, traced=True,
        on_token_factory=lambda i: (
            lambda _tok: base_times[i].append(time.monotonic())))
    base_s = max(time.monotonic() - t0, 1e-9)
    base_steps = engine.decode_steps_total - steps0 - cont_steps
    base_tokens = sum(len(r.tokens) for r in base_reqs)

    tokens_per_s = total_tokens / cont_s
    base_tokens_per_s = base_tokens / base_s

    # the slowest stream's causal path: submit→prefill→decode→finish
    slowest = None
    sl = max(reqs, key=lambda r: (r.last_token_at or 0) - r.submitted_at)
    if sl.trace is not None:
        try:
            from horovod_tpu.diagnostics.flight_recorder import recorder
            from horovod_tpu.tracing.reader import spans_from_events
            spans, _pts = spans_from_events(recorder().events(),
                                            trace_id=sl.trace.trace_id)
            slowest = {
                "trace": sl.trace.trace_id,
                "latency_s": round(sl.last_token_at - sl.submitted_at, 6),
                "hops": [{"name": s["name"], "dur_s": s["dur_s"]}
                         for s in sorted(spans,
                                         key=lambda s: s["start"])],
            }
        except Exception:
            slowest = {"trace": sl.trace.trace_id, "hops": []}

    pool = engine.pool
    return {
        "bench": "serving_generate",
        "tracing_enabled": bool(tracing.enabled()),
        "requests": requests,
        "failed": failed,
        "n_slots": n_slots,
        "prefill_chunk": prefill_chunk,
        "total_tokens": total_tokens,
        "duration_s": round(cont_s, 3),
        "tokens_per_s": round(tokens_per_s, 2),
        "ttft_p50_s": round(percentile(ttfts, 0.50), 6),
        "ttft_p99_s": round(percentile(ttfts, 0.99), 6),
        "itl_p50_s": round(percentile(itls, 0.50), 6),
        "itl_p99_s": round(percentile(itls, 0.99), 6),
        "slot_occupancy_mean": round(
            sum(occupancy) / len(occupancy), 4) if occupancy else 0.0,
        "decode_steps": cont_steps,
        "prefill_chunks": cont_chunks,
        "decode_compiles": compile_watch.per_function_compiles().get(
            "gen_decode_step", 0),
        "kv_page_tokens": pool.plan.page_tokens,
        "kv_pages_total": pool.capacity,
        "kv_pages_high_water": pool.high_water,
        "baseline_tokens_per_s": round(base_tokens_per_s, 2),
        "baseline_decode_steps": base_steps,
        "speedup": round(tokens_per_s / max(base_tokens_per_s, 1e-9), 4),
        "slowest_request_trace": slowest,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="serving_bench")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--warmup", type=float, default=1.0)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--in-process", action="store_true")
    p.add_argument("--generate", action="store_true",
                   help="bench the continuous-batching generate engine "
                        "(emits BENCH_SERVE_GEN)")
    p.add_argument("--requests", type=int, default=24,
                   help="generate mode: request count")
    p.add_argument("--slots", type=int, default=4,
                   help="generate mode: decode slots")
    p.add_argument("--prefill-chunk", type=int, default=8)
    p.add_argument("--out", default=None, help="also write the JSON here")
    args = p.parse_args(argv)
    if args.generate:
        doc = run_gen_bench(requests=args.requests, n_slots=args.slots,
                            prefill_chunk=args.prefill_chunk)
        prefix = "BENCH_SERVE_GEN"
    else:
        doc = run_bench(replicas=args.replicas, clients=args.clients,
                        duration_s=args.duration, dim=args.dim,
                        in_process=args.in_process, warmup_s=args.warmup)
        prefix = "BENCH_SERVE"
    line = json.dumps(doc)
    print(f"{prefix} {line}", flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
