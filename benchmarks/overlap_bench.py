"""Overlap efficiency microbench: exposed-communication seconds per step.

Measures the same data-parallel train step in three schedules on a
multi-device mesh (8 virtual CPU devices by default — the test mesh; a
real TPU slice when run there):

* ``compute``    — collectives replaced by identity (``sync=False``):
                   the pure-compute floor.
* ``serialized`` — bucket count 1 and every reduction pinned onto the
                   critical path before the next microbatch's backward
                   (``overlap=False`` — the reduce-after-backward
                   behavior the ISSUE calls the MFU blocker).
* ``overlap``    — bucketed, software-pipelined reductions issued one
                   iteration behind production (``overlap=True``).

``exposed_comm = step_time(config) − step_time(compute)`` attributes the
collective seconds that did NOT hide behind backward compute. The
overlap schedule must keep exposed_comm strictly below the serialized
schedule — that delta is the whole point of the engine
(docs/PERF.md "Overlap & bucketing").

Results land on the PR-1 metrics registry
(``hvd_overlap_exposed_comm_seconds{config=...}``) and stdout carries
one JSON doc. Run standalone::

    python benchmarks/overlap_bench.py        # 8 virtual CPU devices
"""

from __future__ import annotations

import json
import os
import sys
import time

N_DEVICES = int(os.environ.get("HVD_OVERLAP_BENCH_DEVICES", "8"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":  # force the virtual mesh before jax imports
    sys.path.insert(0, REPO)
    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEVICES}")
    os.environ["JAX_PLATFORMS"] = "cpu"


def _sweep_model(d_model, n_layers):
    """The shared tanh-stack workload: BOTH the schedule comparison and
    the autotune plan sweep time this exact model — the acceptance gate
    compares their numbers, so they must never drift apart."""
    import numpy as np
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    params = {
        f"w{i}": jnp.asarray(
            rng.randn(d_model, d_model).astype(np.float32)
            / np.sqrt(d_model))
        for i in range(n_layers)
    }

    def loss_fn(p, xy):
        x, y = xy
        h = x
        for i in range(n_layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    return params, loss_fn


def _build(mesh, axis_name, d_model, n_layers, n_micro, batch,
           bucket_bytes, config, ring):
    import numpy as np
    import jax.numpy as jnp
    import optax

    from horovod_tpu.train.overlap import make_overlap_train_step

    rng = np.random.RandomState(0)
    params, loss_fn = _sweep_model(d_model, n_layers)

    tx = optax.sgd(1e-3)
    # autotune=False: this bench COMPARES fixed schedules — a
    # fleet-wide HVD_TPU_AUTOTUNE_MESH=1 must not swap in the searcher
    step = make_overlap_train_step(
        loss_fn, tx, mesh, axis_name, n_micro=n_micro,
        bucket_bytes=bucket_bytes, ring=ring,
        overlap=(config == "overlap"), sync=(config != "compute"),
        donate=False, autotune=False)
    x = jnp.asarray(rng.randn(batch, d_model).astype(np.float32))
    y = jnp.asarray(rng.randn(batch, d_model).astype(np.float32))
    opt_state = tx.init(params)
    return step, params, opt_state, (x, y)


def _time_config(mesh, axis_name, config, *, d_model, n_layers, n_micro,
                 batch, bucket_bytes, iters, ring) -> float:
    import jax

    step, params, opt_state, batch_xy = _build(
        mesh, axis_name, d_model, n_layers, n_micro, batch,
        # serialized = the bucketing-off baseline: ONE bucket
        (1 << 62) if config == "serialized" else bucket_bytes,
        config, ring and config == "overlap")
    params, opt_state, loss = step(params, opt_state, batch_xy)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, batch_xy)
    jax.block_until_ready(loss)
    jax.block_until_ready(params)
    return (time.perf_counter() - t0) / iters


def run_overlap_bench(mesh=None, axis_name: str = "dp", *,
                      d_model: int = 256, n_layers: int = 12,
                      n_micro: int = 4, batch_per_device: int = 4,
                      bucket_bytes: int = 128 * 1024, iters: int = 10,
                      ring: bool = False, repeats: int = 3) -> dict:
    """Run all three schedules; returns the result doc (see module
    docstring) and records the exposed-comm gauges. Best-of-``repeats``
    per config so one scheduler hiccup on a loaded box doesn't invert
    the comparison."""
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.metrics.registry import default_registry

    if mesh is None:
        mesh = hvd.build_mesh(dp=-1)
    n_dev = int(np.prod(list(mesh.shape.values())))
    batch = batch_per_device * n_dev * n_micro

    kw = dict(d_model=d_model, n_layers=n_layers, n_micro=n_micro,
              batch=batch, bucket_bytes=bucket_bytes, iters=iters,
              ring=ring)
    times = {}
    for config in ("compute", "serialized", "overlap"):
        times[config] = min(
            _time_config(mesh, axis_name, config, **kw)
            for _ in range(max(1, repeats)))

    reg = default_registry()
    exposed = {}
    for config in ("serialized", "overlap"):
        exposed[config] = max(0.0, times[config] - times["compute"])
        reg.gauge("hvd_overlap_exposed_comm_seconds",
                  help="exposed collective seconds per step by schedule",
                  labels={"config": config}).set(exposed[config])

    # the ACTUAL plan (oversized leaves ride alone — ceil(bytes/budget)
    # would overstate the bucket count for layer-sized leaves)
    import jax
    from horovod_tpu.train.buckets import plan_buckets
    plan = plan_buckets(
        [jax.ShapeDtypeStruct((d_model, d_model), "float32")
         for _ in range(n_layers)], bucket_bytes)
    grad_bytes = plan.total_bytes
    n_buckets = plan.num_buckets
    doc = {
        "metric": "overlap_exposed_comm_seconds_per_step",
        "n_devices": n_dev,
        "n_micro": n_micro,
        "bucket_bytes": bucket_bytes,
        "bucket_count": n_buckets,
        "grad_bytes": grad_bytes,
        "step_s": {k: round(v, 5) for k, v in times.items()},
        "exposed_comm_s": {k: round(v, 5) for k, v in exposed.items()},
        "overlap_beats_serialized":
            exposed["overlap"] < exposed["serialized"],
        "exposed_comm_reduction":
            round(1.0 - exposed["overlap"] / exposed["serialized"], 3)
            if exposed["serialized"] > 0 else None,
    }
    return doc


def run_plan_sweep(mesh=None, axis_name: str = "dp", *,
                   plans=None, d_model: int = 128, n_layers: int = 8,
                   n_micro: int = 2, batch_per_device: int = 4,
                   iters: int = 6, repeats: int = 2) -> dict:
    """Hand-set configuration sweep: measure each candidate
    :class:`~horovod_tpu.train.autotune.Plan` with the SAME step builder
    the autotuner compiles, best-of-``repeats`` wall time per step.

    This is the autotune acceptance baseline (ISSUE 8): the online
    search must lock a plan no worse (within tolerance) than the best
    row of this sweep — it searches the same space with the same
    measurement, so losing to the sweep means the search logic, not the
    hardware, regressed. Returns ``{"plans": {key: s}, "best_plan":
    key, "best_s": s}``.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.common.topology import detect_topology
    from horovod_tpu.train.autotune import candidate_plans
    from horovod_tpu.train.overlap import make_overlap_train_step

    if mesh is None:
        mesh = hvd.build_mesh(dp=-1)
    n_dev = int(np.prod(list(mesh.shape.values())))
    topo = detect_topology(mesh, axis_name)
    if plans is None:
        plans = candidate_plans(topo)
    params, loss_fn = _sweep_model(d_model, n_layers)
    tx = optax.sgd(1e-3)
    rng = np.random.RandomState(1)
    batch = batch_per_device * n_dev * n_micro
    x = jnp.asarray(rng.randn(batch, d_model).astype(np.float32))
    y = jnp.asarray(rng.randn(batch, d_model).astype(np.float32))

    state = {}
    for plan in plans:
        # autotune=False: each row realizes ONE hand-set plan
        step = make_overlap_train_step(
            loss_fn, tx, mesh, axis_name, n_micro=n_micro, donate=False,
            autotune=False, **plan.step_kwargs(topo))
        p, s, loss = step(params, tx.init(params), (x, y))  # compile
        jax.block_until_ready(loss)
        state[plan.key] = (step, p, s)
    times = {plan.key: float("inf") for plan in plans}
    # INTERLEAVE the repeats round-robin across plans: box-load drift
    # (another process ramping up mid-sweep) then penalizes every plan
    # equally instead of whichever happened to be measured last — the
    # best-of over interleaved windows is what makes this sweep a
    # stable baseline for the autotune acceptance gate
    for _ in range(max(1, repeats)):
        for plan in plans:
            step, p, s = state[plan.key]
            t0 = time.perf_counter()
            for _ in range(iters):
                p, s, loss = step(p, s, (x, y))
            jax.block_until_ready(loss)
            times[plan.key] = min(times[plan.key],
                                  (time.perf_counter() - t0) / iters)
            state[plan.key] = (step, p, s)
    best_key = min(times, key=times.get)
    return {"plans": times, "best_plan": best_key,
            "best_s": times[best_key], "n_devices": n_dev}


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import horovod_tpu as hvd
    hvd.init()
    try:
        doc = run_overlap_bench()
        print(json.dumps(doc), flush=True)
        return 0
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
