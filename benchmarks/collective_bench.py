"""Host-plane collective micro-benchmark (nccl-tests / osu-benchmarks
analog for the TCP core).

Measures allreduce algorithm bandwidth across message sizes and world
sizes on localhost workers, the way the reference community benchmarks
its Gloo/MPI CPU path. Algorithm ("bus") bandwidth for ring allreduce is
``2(n-1)/n * bytes / time`` — the wire traffic each rank actually moves.

Run:    python benchmarks/collective_bench.py [--sizes 2,4,8]
                                              [--bytes 4096,...,67108864]
Output: one table row per (world, bytes): latency and busbw, plus a JSON
summary line at the end for scripting.

This measures the HOST data plane (``cpp/collectives.cc`` over the TCP
mesh). On TPU the per-step gradient path rides XLA collectives over ICI
(see ``ops/mesh_collectives.py``); the host plane carries control traffic,
CPU-resident tensors, and the tests, so its bandwidth still matters.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from horovod_tpu.runner.exec_run import free_port  # noqa: E402

WORKER_BODY = r"""
import os, sys, time
sys.path.insert(0, %(repo)r)
import numpy as np
from horovod_tpu.core.core_backend import CoreBackend
from horovod_tpu.ops.reduce_op import ReduceOp

sizes_bytes = [int(s) for s in os.environ["BENCH_BYTES"].split(",")]
iters_env = int(os.environ.get("BENCH_ITERS", "0"))
be = CoreBackend()
out = []
for nbytes in sizes_bytes:
    n = max(nbytes // 4, 1)
    x = np.ones(n, np.float32)
    # warmup
    for i in range(3):
        be.allreduce_async(f"w.{nbytes}.{i}", x, ReduceOp.SUM).wait(120)
    iters = iters_env or (10 if nbytes >= 1 << 22 else 30)
    t0 = time.perf_counter()
    for i in range(iters):
        be.allreduce_async(f"b.{nbytes}.{i}", x, ReduceOp.SUM).wait(300)
    dt = (time.perf_counter() - t0) / iters
    out.append((nbytes, dt))
if be.rank == 0:
    for nbytes, dt in out:
        print(f"RESULT {nbytes} {dt:.6e}", flush=True)
be.shutdown()
"""


def run_world(world: int, sizes_bytes: list, iters: int = 0) -> dict:
    port = free_port()
    procs = []
    try:
        for rank in range(world):
            env = dict(os.environ)
            env.update({
                "BENCH_ITERS": str(iters),
                "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": str(world),
                "HOROVOD_LOCAL_RANK": str(rank),
                "HOROVOD_LOCAL_SIZE": str(world),
                "HOROVOD_CROSS_RANK": "0", "HOROVOD_CROSS_SIZE": "1",
                "HVD_TPU_COORD_ADDR": "127.0.0.1",
                "HVD_TPU_COORD_PORT": str(port),
                "BENCH_BYTES": ",".join(str(b) for b in sizes_bytes),
                "JAX_PLATFORMS": "cpu",
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-c", WORKER_BODY % {"repo": REPO}],
                stdout=subprocess.PIPE if rank == 0 else subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, text=True, env=env))
        out, _ = procs[0].communicate(timeout=1200)
        hung = []
        for i, p in enumerate(procs[1:], start=1):
            try:
                p.wait(timeout=120)
            except subprocess.TimeoutExpired:
                hung.append(i)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
    bad = [(i, p.returncode) for i, p in enumerate(procs)
           if p.returncode != 0]
    if hung or bad:
        raise RuntimeError(
            f"world={world}: hung ranks {hung}, nonzero exits {bad}")
    results = {}
    for line in out.splitlines():
        if line.startswith("RESULT "):
            _, nbytes, dt = line.split()
            results[int(nbytes)] = float(dt)
    if len(results) != len(sizes_bytes):
        raise RuntimeError(
            f"world={world}: expected {len(sizes_bytes)} results, got "
            f"{sorted(results)}")
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="2,4",
                    help="comma-separated world sizes")
    ap.add_argument("--bytes", default=",".join(
        str(1 << p) for p in range(12, 27, 2)),
        help="comma-separated message sizes in bytes")
    args = ap.parse_args()
    worlds = [int(s) for s in args.sizes.split(",")]
    # dedupe, preserving order: results are keyed by size
    sizes_bytes = list(dict.fromkeys(int(b) for b in args.bytes.split(",")))

    print(f"{'world':>5} {'bytes':>10} {'latency_us':>11} {'busbw_GB/s':>11}")
    summary = []
    for world in worlds:
        res = run_world(world, sizes_bytes)
        for nbytes in sizes_bytes:
            dt = res.get(nbytes)
            if dt is None:
                continue
            busbw = 2 * (world - 1) / world * nbytes / dt / 1e9
            print(f"{world:>5} {nbytes:>10} {dt * 1e6:>11.1f} "
                  f"{busbw:>11.3f}")
            summary.append({"world": world, "bytes": nbytes,
                            "latency_s": dt, "busbw_gbps": busbw})
    print(json.dumps({"allreduce_busbw": summary}))


if __name__ == "__main__":
    main()
