"""Rollout transition bench: promote + rollback latency under load.

Emits one ``BENCH_ROLLOUT``-prefixed JSON line (and optionally a file)
— the standing artifact ``ci/check_bench.py --rollout`` gates: how
long a governed fleet transition takes in each direction, measured as
hook-invocation → every live replica observed serving the target
version, plus the zero-drop audit over the WHOLE run (both
transitions ride under sustained closed-loop traffic; a transition
that dropped a request is not 'governed', and the gate refuses the
artifact).

The bench drives the :class:`RolloutController`'s promote/rollback
hooks DIRECTLY (no autopilot in the loop): the standing number
measures the mechanical repin/flip latency, not comparator window
arithmetic — windows are knob-dependent, the flip is the system.

Run:  python benchmarks/rollout_bench.py --out BENCH_ROLLOUT.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _wait_versions(fleet, version: int, timeout_s: float = 30.0) -> bool:
    """Every live slot observed serving ``version`` (readyz probes)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        vs = fleet.versions()
        if vs and all(v == version for v in vs.values()):
            return True
        time.sleep(0.05)
    return False


def run_rollout_bench(replicas: int = 3, clients: int = 4,
                      dim: int = 8) -> dict:
    from horovod_tpu.checkpoint import ShardedCheckpointer
    from horovod_tpu.serving import ReplicaFleet, Router
    from horovod_tpu.serving.replica import demo_params
    from horovod_tpu.serving.rollout import (RolloutConfig,
                                             RolloutController)

    tmp = tempfile.mkdtemp(prefix="hvd_rollout_bench_")
    store = ShardedCheckpointer(tmp, rank=0, world_size=1)
    store.save(1, {"params": demo_params(dim, scale=1.0)}, wait=True)
    fleet = ReplicaFleet(
        size=replicas, dim=dim, store_dir=tmp,
        extra_env={"HVD_TPU_SERVING_SWAP_POLL_S": "0.05"}).start(
        ready_timeout_s=120)
    router = Router(fleet.endpoints, hedge_ms=200, max_attempts=8)
    cfg = RolloutConfig(canary_pct=34, window_s=0.5, min_requests=5)
    ctl = RolloutController(fleet, router, cfg, store_dir=tmp)

    stop = threading.Event()
    errors = []

    def client(i):
        n = 0
        while not stop.is_set():
            n += 1
            try:
                router.submit([float(i)] + [1.0] * (dim - 1),
                              req_id=f"b{i}-{n}")
            except Exception as e:  # noqa: BLE001 - audit catches all
                errors.append(repr(e))
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    promote_s = rollback_s = None
    try:
        time.sleep(1.0)  # warm traffic on the incumbent
        # the candidate commit lands; begin() pins the fleet right
        # after (the brief chase window before the pins land is the
        # production race too — zero-drop must hold through it)
        store.save(2, {"params": demo_params(dim, scale=2.0)},
                   wait=True)
        # transition 1: canary v2, then ROLL BACK to v1
        ctl.begin(candidate=2, incumbent=1)
        time.sleep(0.5)  # split traffic actually flows
        t0 = time.monotonic()
        ctl._on_rollback({"rollout_id": ctl.rollout_id,
                          "reason": "bench"})
        if _wait_versions(fleet, 1):
            rollback_s = round(time.monotonic() - t0, 4)
        # transition 2: canary v2 again, PROMOTE fleet-wide
        ctl.begin(candidate=2, incumbent=1)
        time.sleep(0.5)
        t0 = time.monotonic()
        ctl._on_promote({"rollout_id": ctl.rollout_id})   # -> 50%
        ctl._on_promote({"rollout_id": ctl.rollout_id})   # -> fleet
        if _wait_versions(fleet, 2):
            promote_s = round(time.monotonic() - t0, 4)
        time.sleep(0.5)  # post-transition traffic on the new version
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=15)
        router.close()
    acct = router.accounting()
    fleet.stop()
    store.close()
    return {
        "bench": "rollout",
        "replicas": replicas,
        "clients": clients,
        "requests": acct["accepted"],
        "failed": acct["outcomes"].get("failed", 0)
        + len(errors),
        "unanswered": len(acct["unanswered"]),
        "answered_twice": len(acct["answered_twice"]),
        "by_version": acct["by_version"],
        "promote_s": promote_s,
        "rollback_s": rollback_s,
        "final_state": ctl.state,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rollout_bench")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--dim", type=int, default=8)
    p.add_argument("--out", default=None, help="also write the JSON here")
    args = p.parse_args(argv)
    doc = run_rollout_bench(replicas=args.replicas,
                            clients=args.clients, dim=args.dim)
    line = json.dumps(doc)
    print(f"BENCH_ROLLOUT {line}", flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
