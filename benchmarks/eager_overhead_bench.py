"""Eager-adapter overhead micro-benchmark (VERDICT r3 weak #6).

The drop-in Torch/TF adapters issue one EAGER collective per call
through the process backend — per-call negotiation, host-memory copies,
TCP wire time (``ops/backend.py`` dispatch over the C++ core; under
``HOROVOD_TPU_OPERATIONS=XLA_EAGER`` additional device<->host
``device_put`` round-trips stack on top, so the numbers here are a
LOWER bound on adapter overhead). The native JAX path compiles the
collective INTO the step (``ops/mesh_collectives.py`` jit-cached
shard_map programs). This harness quantifies that gap so "drop-in
Horovod on TPU" users know what the eager convenience costs and when to
move the hot loop in-graph (``docs/MIGRATION.md``).

Three timings per tensor size, same math (global SUM):
- ``ingraph``:   jitted shard_map allreduce replayed from cache
                 (``device_allreduce``) — the native per-step path;
- ``eager``:     a REAL 2-process eager allreduce through the TCP core
                 (via ``collective_bench.run_world``, always host CPU
                 processes — per-call negotiation + host copies, the
                 path the Torch/TF adapters ride; the single-process
                 LOCAL backend short-circuits and would measure
                 nothing);
- ``step_fused``: the same reduction fused into a jitted
                 compute+update step — what a real training step pays
                 (the collective rides the step's compilation, so the
                 adapter-vs-native gap is pure launch overhead).

Run:    python benchmarks/eager_overhead_bench.py [--bytes ...]
Output: a table + one JSON summary line (eager_overhead).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if not os.environ.get("HVD_BENCH_TPU"):  # default: 8-device CPU mesh
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu.core import core_available  # noqa: E402
from horovod_tpu.ops.mesh_collectives import device_allreduce  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from collective_bench import run_world  # noqa: E402


def _time(fn, readback, iters):
    fn()  # compile / warm path
    readback()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    readback()
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bytes", default=",".join(
        str(1 << p) for p in range(12, 25, 4)))
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    hvd.init()
    mesh = hvd.build_mesh(dp=-1)

    sizes_bytes = [int(b) for b in args.bytes.split(",")]
    # the eager column: real 2-process negotiation + host copies
    eager_lat = {}
    if core_available():
        import subprocess
        try:
            eager_lat = run_world(2, sizes_bytes, iters=args.iters)
        except (RuntimeError, OSError,
                subprocess.SubprocessError) as e:  # died / hung / port race
            print(f"WARNING: eager workers failed ({e}); eager column "
                  "omitted", file=sys.stderr)
    else:
        print("WARNING: libhvdcore.so not built — eager column omitted "
              "(build with `make -C cpp`)", file=sys.stderr)

    print(f"devices: {jax.device_count()}x {jax.devices()[0].device_kind}")
    print(f"{'bytes':>10} {'ingraph_us':>11} {'eager_us':>10} "
          f"{'fused_us':>10} {'eager_x':>8}")
    results = []
    last = {}
    n_dev = jax.device_count()
    for nbytes in sizes_bytes:
        rows = max(nbytes // 4 // n_dev, 1)
        # in-graph contract: leading dim = mesh axis size, one shard/row
        xs = jax.device_put(jnp.ones((n_dev, rows), jnp.float32),
                            hvd.batch_sharding(mesh))

        out = {}

        def ingraph():
            out["v"] = device_allreduce(xs, mesh)

        @jax.jit
        def fused_step(x):
            y = x * 2.0 - 1.0  # stand-in compute
            return device_allreduce(y, mesh) * 0.5

        def fused():
            out["v"] = fused_step(xs)

        def readback():
            np.asarray(out["v"])  # host sync: the only reliable fence

        t_in = _time(ingraph, readback, args.iters)
        t_eager = eager_lat.get(nbytes)  # None when the core isn't built
        t_fused = _time(fused, readback, args.iters)
        ratio = (t_eager / t_fused) if (t_eager and t_fused) else None
        print(f"{nbytes:>10} {t_in * 1e6:>11.1f} "
              f"{t_eager * 1e6 if t_eager else float('nan'):>10.1f} "
              f"{t_fused * 1e6:>10.1f} "
              f"{ratio if ratio else float('nan'):>8.1f}")
        results.append({"bytes": nbytes, "ingraph_s": t_in,
                        "eager_s": t_eager, "fused_step_s": t_fused,
                        "eager_over_fused": ratio})
        last = results[-1]

    print(json.dumps({
        "eager_overhead": results,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": jax.device_count(),
        "headline_eager_over_fused": last.get("eager_over_fused"),
    }))
    hvd.shutdown()


if __name__ == "__main__":
    main()
