"""Pipeline schedule microbench: measured step seconds per schedule.

Times the SAME layer-major model through
:func:`horovod_tpu.train.pipeline.make_pipeline_train_step` under each
pipeline schedule at a fixed (pp, n_microbatches):

* ``gpipe``       — all forwards then autodiff backward. Fewest
                    tick-slots on an SPMD mesh (each pass pays its own
                    fill bubble once), but the live-residual stack grows
                    with M.
* ``1f1b``        — combined fwd+bwd ticks with the remat ring: bounded
                    activation memory, at the price of the combined
                    bubble ``2(S-1)`` ticks and the remat recompute.
* ``interleaved`` — 1F1B with ``v`` virtual chunks per device: the same
                    bounded memory with a ``~1/v`` smaller bubble —
                    strictly fewer compute-unit-ticks than plain 1F1B
                    at the same M (docs/PERF.md "Pipeline parallelism").

Repeats are INTERLEAVED round-robin across schedules (the PR-8 sweep
design): box-load drift penalizes every schedule equally, and the
best-of over interleaved windows is what the acceptance gate in
``tests/test_parallel_plan.py`` asserts on. Each measurement also
reports the schedule's ANALYTIC bubble fraction, and — via a pp=1
compute-only baseline riding the same interleaved repeats (same
per-device work, zero pipeline dependencies; the overlap_bench
attribution pattern) — the MEASURED bubble per schedule
(``bubble_measured``), so analytic-vs-measured drift is a recorded
number, not a guess.

Run standalone::

    python benchmarks/pipeline_bench.py       # 8 virtual CPU devices
"""

from __future__ import annotations

import json
import os
import sys
import time

N_DEVICES = int(os.environ.get("HVD_PIPELINE_BENCH_DEVICES", "8"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":  # force the virtual mesh before jax imports
    sys.path.insert(0, REPO)
    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEVICES}")
    os.environ["JAX_PLATFORMS"] = "cpu"

SCHEDULES = ("gpipe", "1f1b", "interleaved")


def _sweep_model(d_model, n_layers):
    """Layer-major tanh-matmul stack (the factory's model contract):
    every leaf carries the layer dim, one matmul per layer."""
    import numpy as np
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(
        rng.randn(n_layers, d_model, d_model).astype(np.float32)
        / np.sqrt(d_model))}

    def layer_fn(lp, x):
        return jnp.tanh(x @ lp["w"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    return params, layer_fn, loss_fn


def run_schedule_sweep(mesh=None, *, pp: int = 4, virtual_stages: int = 2,
                       n_micro: int = 8, d_model: int = 384,
                       n_layers: int = 8, rows_per_microbatch: int = 16,
                       iters: int = 4, repeats: int = 3,
                       schedules=SCHEDULES) -> dict:
    """Measure each schedule, best-of interleaved repeats. Returns
    ``{"schedules": {name: s}, "bubble": {name: frac}, ...}``."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.parallel.pipeline import bubble_fraction
    from horovod_tpu.train.pipeline import make_pipeline_train_step

    if mesh is None:
        mesh = hvd.dp_pp_mesh(pp=pp)
    n_dev = int(np.prod(list(mesh.shape.values())))
    dp = n_dev // pp
    params, layer_fn, loss_fn = _sweep_model(d_model, n_layers)
    tx = optax.sgd(1e-3)
    rng = np.random.RandomState(1)
    batch = dp * n_micro * rows_per_microbatch
    x = jnp.asarray(rng.randn(batch, d_model).astype(np.float32))
    y = jnp.asarray(rng.randn(batch, d_model).astype(np.float32))

    state = {}
    configs = list(schedules)
    # compute-only baseline (ISSUE 12 satellite): pp=1 on the SAME
    # devices with the SAME global batch does exactly the per-device
    # work of a zero-bubble pipeline (n_layers*M*rows/pp either way)
    # with no cross-stage dependency — the overlap_bench attribution
    # pattern, so 1 - t_compute/t_schedule is the MEASURED bubble.
    # Needs rows_per_microbatch % pp == 0 so the pp=1 mesh can
    # re-microbatch the same batch.
    measure_compute = rows_per_microbatch % pp == 0
    if measure_compute:
        configs.append("compute")
    for config in configs:
        if config == "compute":
            # the SAME devices as the sweep mesh (a caller-supplied
            # sub-mesh must keep per-device work identical, or the
            # measured bubble silently inflates), flattened onto dp
            step = make_pipeline_train_step(
                layer_fn, loss_fn, tx, n_layers=n_layers,
                mesh=hvd.dp_pp_mesh(
                    pp=1, devices=list(mesh.devices.flat)),
                pp=1, n_micro=n_micro,
                donate=False, autotune=False)
        else:
            v = virtual_stages if config == "interleaved" else 1
            step = make_pipeline_train_step(
                layer_fn, loss_fn, tx, n_layers=n_layers, mesh=mesh,
                schedule=config, pp=pp, n_micro=n_micro,
                virtual_stages=v, donate=False, autotune=False)
        p = step.prepare_params(params)
        s = step.prepare_params(tx.init(params))
        p, s, loss = step(p, s, (x, y))          # compile
        jax.block_until_ready(loss)
        state[config] = (step, p, s)
    times = {config: float("inf") for config in configs}
    for _ in range(max(1, repeats)):
        for config in configs:
            step, p, s = state[config]
            t0 = time.perf_counter()
            for _ in range(iters):
                p, s, loss = step(p, s, (x, y))
            jax.block_until_ready(loss)
            jax.block_until_ready(p)
            times[config] = min(times[config],
                                (time.perf_counter() - t0) / iters)
            state[config] = (step, p, s)
    doc = {
        "metric": "pipeline_schedule_step_seconds",
        "n_devices": n_dev, "dp": dp, "pp": pp,
        "virtual_stages": virtual_stages, "n_micro": n_micro,
        "d_model": d_model, "n_layers": n_layers,
        "schedules": {k: round(v, 5) for k, v in times.items()
                      if k != "compute"},
        "bubble": {
            s: round(bubble_fraction(
                s, pp, n_micro,
                virtual_stages if s == "interleaved" else 1), 4)
            for s in schedules},
    }
    if measure_compute:
        t_c = times["compute"]
        doc["compute_step_s"] = round(t_c, 5)
        # measured vs analytic drift per schedule: remat recompute and
        # collective latency the tick model cannot see land here
        doc["bubble_measured"] = {
            s: round(max(0.0, 1.0 - t_c / times[s]), 4)
            for s in schedules if times[s] > 0}
    return doc


def main() -> int:
    doc = run_schedule_sweep()
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
