"""Data loading helpers.

Reference: ``horovod/data/data_loader_base.py`` (``BaseDataLoader`` and
``AsyncDataLoaderMixin`` — a background-thread prefetch queue, :23-151).
TPU additions: :class:`ShardedDataset` for per-worker sharding (the
reference leaves sharding to torch's DistributedSampler) and device
prefetch hooks (host→HBM transfer overlapped with compute).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Iterator, Optional, Sequence

import jax


class BaseDataLoader:
    """Iteration contract (reference: ``BaseDataLoader:23-60``)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Background-thread prefetch (reference: ``AsyncDataLoaderMixin:63-151``).

    Mix in FIRST: ``class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader)``.
    ``async_loader_queue_size=0`` disables prefetch (synchronous).
    """

    def __init__(self, *args: Any, async_loader_queue_size: int = 64,
                 **kwargs: Any) -> None:
        self._queue_size = async_loader_queue_size
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._closing = False
        super().__init__(*args, **kwargs)

    def close_async_loader(self) -> None:
        """Reference: ``close_async_loader`` — drain and join."""
        self._closing = True
        if self._q is not None:
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    class _End:
        def __init__(self, error=None):
            self.error = error

    def _put(self, item) -> bool:
        """Bounded put that aborts when the loader is closing (so the
        producer can never wedge in a full queue)."""
        while not self._closing:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        error = None
        try:
            for item in super()._iterate():
                if not self._put(item):
                    return
        except BaseException as e:  # surface loader errors to the consumer
            error = e
        self._put(self._End(error))

    def __iter__(self) -> Iterator[Any]:
        if self._queue_size <= 0:
            yield from super()._iterate()
            return
        self._closing = False
        self._q = queue.Queue(self._queue_size)
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        while True:
            item = self._q.get()
            if isinstance(item, AsyncDataLoaderMixin._End):
                if item.error is not None:
                    raise item.error
                break
            yield item


def device_prefetch(iterator, sharding=None, buffer_size: int = 2):
    """Keep ``buffer_size`` batches RESIDENT ON DEVICE ahead of the
    consumer (double-buffered by default): ``jax.device_put`` is
    asynchronous, so the host→HBM transfer of the next batches overlaps
    the compute consuming the current one and H2D drops off the step's
    critical path (docs/PERF.md headroom (c); the reference's analog is
    the CUDA-stream prefetch users pair with its AsyncDataLoaderMixin).

    ``sharding`` places each leaf (e.g. ``hvd.batch_sharding(mesh)`` for
    dp-sharded batches); ``None`` uses the default device. When the
    sharding spans devices of OTHER processes too (a multi-host mesh),
    each process's batch is treated as its process-local shard and the
    global array is assembled with
    ``jax.make_array_from_process_local_data`` — so the documented
    ShardedDataset-per-rank + ``batch_sharding(mesh)`` stack is correct
    on pods as well. Works on any iterator of pytrees — stack with
    :class:`AsyncDataLoaderMixin` so the HOST side (decode/augment) is
    also off the critical path: background thread feeds
    ``device_prefetch`` feeds the step.

    If the source iterator raises mid-stream, batches already
    transferred are yielded first; the error surfaces at its true
    position in the stream."""
    if buffer_size < 1:
        # eager: a generator would defer this to the first next() deep
        # inside the training loop, far from the misconfigured call
        raise ValueError(f"buffer_size={buffer_size} must be >= 1")
    return _device_prefetch_gen(iter(iterator), sharding, buffer_size)


def _device_prefetch_gen(it, sharding, buffer_size: int):
    q: "collections.deque" = collections.deque()
    pending_error = None

    if sharding is not None and not getattr(
            sharding, "is_fully_addressable", True):
        # multi-host mesh: this process holds only ITS shard of the
        # global batch
        def place(batch):
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    sharding, x), batch)
    else:
        def place(batch):
            # device_put takes the whole pytree: one dispatch per batch
            return jax.device_put(batch, sharding)

    def put_next() -> bool:
        nonlocal pending_error
        if pending_error is not None:
            return False
        try:
            batch = next(it)
        except StopIteration:
            return False
        except BaseException as e:
            # drain the already-transferred batches before surfacing it
            pending_error = e
            return False
        q.append(place(batch))
        return True

    for _ in range(buffer_size):
        if not put_next():
            break
    while q:
        out = q.popleft()
        put_next()  # enqueue the NEXT transfer before handing this one out
        yield out
    if pending_error is not None:
        raise pending_error


class ShardedDataset(BaseDataLoader):
    """Deterministic per-worker shard of an indexable dataset: worker r of n
    sees items ``r, r+n, r+2n, ...`` after an epoch-seeded shuffle — the
    sharding contract of torch's DistributedSampler that reference users
    pair with hvd (``torch/elastic/sampler.py`` is its elastic variant)."""

    def __init__(self, data: Sequence[Any], rank: int, size: int,
                 shuffle: bool = True, seed: int = 0) -> None:
        self._data = data
        self._rank = rank
        self._size = max(size, 1)
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._cursor = 0  # items this worker yielded in the current epoch
        self._resume_skip = 0

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle per epoch (reference: ``ElasticSampler.set_epoch``).
        Re-announcing the CURRENT epoch keeps the restored cursor — the
        standard resume loop (``load_state_dict`` then ``set_epoch``
        inside the epoch loop) must not replay committed items."""
        if epoch != self._epoch:
            self._cursor = 0
            self._resume_skip = 0
        self._epoch = epoch

    def state_dict(self) -> dict:
        """Checkpointable data position: ``{"epoch", "cursor"}`` —
        ``cursor`` counts the items THIS worker has yielded in the
        current epoch, so data position rides the same commit as model
        state (reference analog: ``ElasticSampler.state_dict``).  With a
        prefetching wrapper the cursor counts items handed to the
        prefetcher, which can run a few batches ahead of the consumer —
        commit ordering, not a correctness issue."""
        return {"epoch": self._epoch, "cursor": self._cursor}

    def load_state_dict(self, state: dict) -> None:
        """Resume mid-epoch: the next iteration replays the epoch's
        deterministic order and skips the first ``cursor`` items.  The
        cursor is per-worker: after an elastic world-size change start
        from the next epoch boundary instead (the shard stride changed,
        so mid-epoch positions don't map)."""
        self._epoch = int(state["epoch"])
        self._cursor = int(state.get("cursor", 0))
        self._resume_skip = self._cursor

    def __len__(self) -> int:
        return len(self._data) // self._size

    def _iterate(self) -> Iterator[Any]:
        import numpy as np
        idx = np.arange(len(self._data))
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            rng.shuffle(idx)
        n = len(self) * self._size  # drop remainder so all workers agree
        skip, self._resume_skip = self._resume_skip, 0
        self._cursor = skip
        for pos, i in enumerate(idx[self._rank:n:self._size]):
            if pos < skip:
                continue
            self._cursor = pos + 1
            yield self._data[int(i)]
        # a completed epoch resets the position (an abandoned iterator —
        # e.g. a mid-epoch checkpoint + crash — keeps its cursor)
        self._cursor = 0
