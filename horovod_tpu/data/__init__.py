from horovod_tpu.data.data_loader import (  # noqa: F401
    AsyncDataLoaderMixin,
    BaseDataLoader,
    ShardedDataset,
    device_prefetch,
)
