"""Per-request stage ledger: latency attribution whose books must close.

The serving plane's latency histograms say *how slow*; this module says
*where*.  Every accepted request's wall-clock is decomposed into named
stages — router (``admission``/``hedge_wait``/``dispatch``), replica
(``queue``/``batch_wait``/``forward``/``response``) and generate
(``slot_wait``/``page_wait``/``prefill``/``decode``/``swap_pause``) —
plus an explicit ``unattributed`` residual, mirroring the goodput
ledger's closed-books discipline (docs/OBSERVABILITY.md "Serving
request ledger") on the request plane: the stages must sum to the
end-to-end latency, and whatever they do not cover is *named* as
residual instead of silently vanishing.

Three pieces live here:

* :func:`quantile` — THE one nearest-rank quantile implementation
  (fraction ``q`` in ``[0, 1]``).  The SLO plane's p99, the rollout
  comparator's per-version p99 and the bench artifact's p99 gated by
  ``ci/check_bench.py --serving`` all route through it, so "p99" means
  the same thing everywhere.
* :class:`WindowBooks` + :class:`ExemplarRing` — per-window stage
  aggregation (sums, shares, dominant stage) and a bounded ring of
  tail exemplars: the worst requests per window with trace id + full
  stage breakdown, dumped into the autopsy bundle and served at
  ``/debug/exemplars``.
* :class:`BurnRateSlo` — multi-window burn-rate alerting over an error
  budget, replacing the single-threshold p99 check: a breach episode
  opens when BOTH the fast and the slow window burn their budget above
  ``HVD_TPU_SERVING_BURN_THRESHOLD``, the finding names the dominant
  stage (so autopilot can tell a scale-out-shaped breach from a
  swap/KV-shaped one), and hysteresis keeps it to one finding per
  episode.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.metrics.registry import default_registry

#: canonical stage names, in pipeline order.  ``unattributed`` is the
#: explicit residual (e2e minus everything attributed) — always last.
ROUTER_STAGES = ("admission", "hedge_wait", "dispatch")
REPLICA_STAGES = ("queue", "batch_wait", "forward", "response")
GENERATE_STAGES = ("slot_wait", "page_wait", "prefill", "decode",
                   "swap_pause")
RESIDUAL = "unattributed"
STAGES: Tuple[str, ...] = (ROUTER_STAGES + REPLICA_STAGES
                           + GENERATE_STAGES + (RESIDUAL,))

#: stage histogram buckets: stages bottom out well under a millisecond
#: (a decode step's share of one token, a lock acquire), so the floor
#: sits below the request-latency buckets'
STAGE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def quantile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over an ASCENDING-sorted sequence,
    ``q`` a fraction in ``[0, 1]``; 0.0 on empty input."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def close_books(e2e_s: float, stages: Dict[str, float]) -> Dict[str, float]:
    """Return ``stages`` with the ``unattributed`` residual filled in:
    ``max(0, e2e - sum(attributed))``.  Negative stage values are
    clamped to zero (a clock race is an attribution error, not negative
    time)."""
    out = {k: max(0.0, float(v)) for k, v in stages.items()
           if k != RESIDUAL}
    attributed = sum(out.values())
    out[RESIDUAL] = max(0.0, float(e2e_s) - attributed)
    return out


def residual_fraction(e2e_s: float, stages: Dict[str, float]) -> float:
    """Fraction of ``e2e_s`` the named stages do NOT cover (the
    books-close number ``check_bench --serving`` gates < 10%)."""
    if e2e_s <= 0:
        return 0.0
    attributed = sum(max(0.0, float(v)) for k, v in stages.items()
                     if k != RESIDUAL)
    return max(0.0, e2e_s - attributed) / e2e_s


def dominant_stage(stages: Dict[str, float]) -> Optional[str]:
    """The named (non-residual) stage with the largest share; None when
    nothing is attributed."""
    named = {k: v for k, v in stages.items()
             if k != RESIDUAL and v > 0}
    if not named:
        return None
    return max(named.items(), key=lambda kv: kv[1])[0]


def observe_stage_seconds(stages: Dict[str, float]) -> None:
    """Publish one ``hvd_serving_stage_seconds{stage=...}`` observation
    per named stage of one request."""
    reg = default_registry()
    for name, v in stages.items():
        if v <= 0 and name != RESIDUAL:
            continue
        reg.histogram("hvd_serving_stage_seconds",
                      help="per-request wall seconds attributed to one "
                           "named serving stage (the request ledger; "
                           "stage=unattributed is the residual)",
                      labels={"stage": name},
                      buckets=STAGE_BUCKETS).observe(max(0.0, float(v)))


def publish_stage_shares(shares: Dict[str, float]) -> None:
    """Publish the windowed ``hvd_serving_stage_share{stage=...}``
    gauges for EVERY canonical stage — absent stages publish 0.0, so an
    idle window zeroes the shares instead of freezing them."""
    reg = default_registry()
    for name in STAGES:
        reg.gauge("hvd_serving_stage_share",
                  help="fraction of windowed request wall-clock "
                       "attributed to one named stage (0 when idle)",
                  labels={"stage": name}).set(
            float(shares.get(name, 0.0)))


# ---------------------------------------------------------------------------
# Tail exemplars
# ---------------------------------------------------------------------------
class ExemplarRing:
    """Bounded ring of tail exemplars: the worst requests per closed
    window, each carrying trace id + full stage breakdown.  Capacity
    ``HVD_TPU_SERVING_EXEMPLARS`` (default 32); oldest evicted first."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity if capacity \
            else max(1, env_int("SERVING_EXEMPLARS", 32))
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def add(self, exemplar: dict) -> None:
        with self._lock:
            self._ring.append(dict(exemplar))

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def worst(self, n: int = 5) -> List[dict]:
        """The ``n`` slowest exemplars currently held, slowest first."""
        return sorted(self.snapshot(),
                      key=lambda e: e.get("e2e_s", 0.0),
                      reverse=True)[:n]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_default_ring: Optional[ExemplarRing] = None
_default_ring_lock = threading.Lock()


def default_ring() -> ExemplarRing:
    """The process-wide exemplar ring (what ``/debug/exemplars`` and the
    autopsy bundle dump)."""
    global _default_ring
    with _default_ring_lock:
        if _default_ring is None:
            _default_ring = ExemplarRing()
        return _default_ring


def exemplars() -> List[dict]:
    return default_ring().snapshot()


def reset() -> None:
    """Drop the process-wide ring (tests)."""
    global _default_ring
    with _default_ring_lock:
        _default_ring = None


# ---------------------------------------------------------------------------
# Per-window stage books
# ---------------------------------------------------------------------------
class WindowBooks:
    """Accumulates one window's stage sums + the window's worst
    requests; :meth:`close` returns the stage section of the window doc
    and the exemplars to push into the ring.  NOT thread-safe — callers
    (``LatencyWindow``) hold their own lock."""

    def __init__(self, exemplars_per_window: Optional[int] = None) -> None:
        self.exemplars_per_window = exemplars_per_window \
            if exemplars_per_window is not None \
            else max(1, env_int("SERVING_EXEMPLARS_PER_WINDOW", 3))
        self._reset()

    def _reset(self) -> None:
        self._stage_sums: Dict[str, float] = {}
        self._e2e_sum = 0.0
        self._ttfts: List[float] = []
        self._worst: List[dict] = []  # kept sorted, slowest first

    def add(self, seconds: float, stages: Optional[Dict[str, float]],
            trace: Optional[str] = None, req_id: Optional[str] = None,
            version: Optional[int] = None,
            ttft_s: Optional[float] = None) -> None:
        self._e2e_sum += max(0.0, seconds)
        closed = close_books(seconds, stages or {})
        for name, v in closed.items():
            self._stage_sums[name] = self._stage_sums.get(name, 0.0) + v
        if ttft_s is not None:
            self._ttfts.append(float(ttft_s))
        ex = {"e2e_s": round(seconds, 6), "stages":
              {k: round(v, 6) for k, v in closed.items() if v > 0}}
        if trace:
            ex["trace"] = trace
        if req_id:
            ex["req_id"] = req_id
        if version is not None:
            ex["version"] = version
        if ttft_s is not None:
            ex["ttft_s"] = round(ttft_s, 6)
        dom = dominant_stage(closed)
        if dom:
            ex["dominant_stage"] = dom
        self._worst.append(ex)
        self._worst.sort(key=lambda e: e["e2e_s"], reverse=True)
        del self._worst[self.exemplars_per_window:]

    def close(self) -> Tuple[dict, List[dict]]:
        """Close the window's books: returns ``(stage_doc, exemplars)``
        and resets.  ``stage_doc`` carries ``stages`` (summed seconds),
        ``stage_shares`` (fractions of attributed+residual wall-clock),
        ``unattributed_s``/``unattributed_frac`` and
        ``dominant_stage`` — all zero/None on an idle window."""
        sums, e2e, ttfts, worst = (self._stage_sums, self._e2e_sum,
                                   self._ttfts, self._worst)
        self._reset()
        shares = {k: (v / e2e if e2e > 0 else 0.0)
                  for k, v in sums.items()}
        unattrib = sums.get(RESIDUAL, 0.0)
        doc = {
            "stages": {k: round(v, 6) for k, v in sums.items() if v > 0},
            "stage_shares": {k: round(v, 4) for k, v in shares.items()
                             if v > 0},
            "unattributed_s": round(unattrib, 6),
            "unattributed_frac": round(unattrib / e2e, 4)
            if e2e > 0 else 0.0,
            "dominant_stage": dominant_stage(sums),
        }
        if ttfts:
            ttfts.sort()
            doc["ttft_p50_s"] = round(quantile(ttfts, 0.50), 6)
            doc["ttft_p99_s"] = round(quantile(ttfts, 0.99), 6)
        if worst:
            doc["worst_trace"] = worst[0].get("trace")
        return doc, worst


# ---------------------------------------------------------------------------
# Burn-rate SLO
# ---------------------------------------------------------------------------
class BurnRateSlo:
    """Multi-window error-budget burn-rate alerting (docs/OBSERVABILITY.md
    "Burn-rate SLOs").

    A request is *bad* when its latency exceeds
    ``HVD_TPU_SERVING_SLO_P99_MS``; the budget says what fraction of
    requests may be bad (``HVD_TPU_SERVING_ERROR_BUDGET``, default 1%).
    Burn rate = bad-fraction / budget over a window span.  A breach
    episode opens — ONE ``slo_breach`` finding — when the fast span
    (last ``HVD_TPU_SERVING_SLO_WINDOWS`` windows) AND the slow span
    (last ``HVD_TPU_SERVING_BURN_SLOW_WINDOWS``) both burn above
    ``HVD_TPU_SERVING_BURN_THRESHOLD`` and the closing window is itself
    over budget (onset confirmation: a recovered window never opens an
    episode).  The episode re-arms once the fast span burns under 1.0
    (the budget is no longer being spent faster than earned)."""

    def __init__(self, slo_p99_s: Optional[float] = None,
                 budget: Optional[float] = None,
                 fast_windows: Optional[int] = None,
                 slow_windows: Optional[int] = None,
                 threshold: Optional[float] = None) -> None:
        self.slo_p99_s = slo_p99_s if slo_p99_s is not None \
            else env_float("SERVING_SLO_P99_MS", 0.0) / 1000.0
        self.budget = budget if budget is not None \
            else min(1.0, max(1e-6, env_float("SERVING_ERROR_BUDGET",
                                              0.01)))
        self.fast_windows = fast_windows if fast_windows \
            else max(1, env_int("SERVING_SLO_WINDOWS", 2))
        self.slow_windows = slow_windows if slow_windows \
            else max(self.fast_windows,
                     env_int("SERVING_BURN_SLOW_WINDOWS", 12))
        self.threshold = threshold if threshold is not None \
            else env_float("SERVING_BURN_THRESHOLD", 10.0)
        self._history: deque = deque(maxlen=self.slow_windows)
        self._active = False

    @property
    def enabled(self) -> bool:
        return self.slo_p99_s > 0

    def is_bad(self, latency_s: float) -> bool:
        return self.enabled and latency_s > self.slo_p99_s

    @staticmethod
    def _burn(entries, budget: float) -> float:
        requests = sum(r for r, _ in entries)
        bad = sum(b for _, b in entries)
        if requests <= 0:
            return 0.0
        return (bad / requests) / budget

    def observe_window(self, requests: int, bad: int,
                       doc: Optional[dict] = None) -> Optional[dict]:
        """Feed one closed window; returns the finding's fields when
        this window opened a breach episode, else None."""
        if not self.enabled:
            return None
        self._history.append((int(requests), int(bad)))
        fast = list(self._history)[-self.fast_windows:]
        burn_fast = self._burn(fast, self.budget)
        burn_slow = self._burn(self._history, self.budget)
        if self._active and burn_fast < 1.0:
            # budget is being earned back faster than spent: re-arm
            self._active = False
        window_over = requests > 0 and (bad / requests) > self.budget
        if (len(self._history) >= self.fast_windows and window_over
                and burn_fast >= self.threshold
                and burn_slow >= self.threshold
                and not self._active):
            self._active = True
            fields = {
                "slo_s": self.slo_p99_s,
                "budget": self.budget,
                "burn_fast": round(burn_fast, 2),
                "burn_slow": round(burn_slow, 2),
                "bad": bad, "requests": requests,
            }
            if doc:
                for k in ("p99_s", "qps", "shed", "dominant_stage",
                          "worst_trace"):
                    if doc.get(k) is not None:
                        fields[k] = doc[k]
                share = (doc.get("stage_shares") or {}).get(
                    doc.get("dominant_stage") or "", None)
                if share is not None:
                    fields["dominant_share"] = share
            try:
                from horovod_tpu.metrics.anomaly import report_finding
                report_finding("slo_breach", **fields)
            except Exception:
                pass
            return fields
        return None

    @property
    def active(self) -> bool:
        return self._active
