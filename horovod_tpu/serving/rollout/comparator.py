"""Per-version SLO comparison: the measurement half of a rollout.

The comparator never touches the fleet — it reads the router's request
log (every ``ok`` line carries the weight version that answered, every
``retried`` line carries the version that failed first) and reduces a
window of it to per-version latency/error stats, then renders a
verdict:

* ``"rollback"`` — the candidate degraded p99 beyond the allowed ratio
  of the incumbent's, pushed its error rate over the cap, or (the
  quality probe) diverged from the incumbent on the golden request set
  beyond the allowed max.  Latency windows can't see silently-wrong
  MATH — weights that diverge numerically serve just as fast — which
  is why the golden probe exists.
* ``"promote"`` — both arms observed at least ``min_requests``, and
  the candidate held up.
* ``None`` — not enough evidence yet (either arm under
  ``min_requests``): keep serving, keep measuring.  An under-observed
  canary must never promote OR roll back on noise.

Verdicts are therefore auditable from the request log alone
(docs/SERVING.md "Canary rollout"): replaying the same window through
:func:`version_windows` + :func:`compare` reproduces the decision.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

# one quantile implementation serves the whole SLO plane (the
# LatencyWindow, this comparator, and ci/check_bench --serving): a
# verdict replayed through any of them sees the same p99
from horovod_tpu.serving.ledger import dominant_stage, quantile as percentile

Endpoint = Tuple[str, int]


def version_windows(entries: Sequence[dict], versions: Sequence[int]
                    ) -> Dict[int, dict]:
    """Reduce request-log ``entries`` to per-version stats for each of
    ``versions``: ok count, latency p50/p99, the error count
    attributed to the version (``retried`` lines name the version that
    failed via ``after_version``; terminal ``failed`` lines count
    against the version of the last retry target when known), and —
    when the ``ok`` lines carry the request ledger's ``stages`` dict —
    the per-version stage shares plus the dominant stage, so a rollback
    verdict can say WHERE the canary spends its extra latency."""
    wanted = {int(v) for v in versions}
    lat: Dict[int, List[float]] = {v: [] for v in wanted}
    ok: Dict[int, int] = {v: 0 for v in wanted}
    errs: Dict[int, int] = {v: 0 for v in wanted}
    stage_s: Dict[int, Dict[str, float]] = {v: {} for v in wanted}
    for e in entries:
        out = e.get("outcome")
        if out == "ok":
            v = e.get("version")
            if v in wanted:
                ok[v] += 1
                if isinstance(e.get("latency_s"), (int, float)):
                    lat[v].append(float(e["latency_s"]))
                st = e.get("stages")
                if isinstance(st, dict):
                    acc = stage_s[v]
                    for k, dur in st.items():
                        if isinstance(dur, (int, float)):
                            acc[k] = acc.get(k, 0.0) + float(dur)
        elif out == "retried":
            av = e.get("after_version")
            if av in wanted:
                errs[av] += 1
    stats: Dict[int, dict] = {}
    for v in wanted:
        xs = sorted(lat[v])
        n = ok[v] + errs[v]
        stats[v] = {
            "version": v,
            "requests": n,
            "ok": ok[v],
            "errors": errs[v],
            "error_rate": round(errs[v] / n, 6) if n else 0.0,
            # percentile() takes a FRACTION in [0,1] (the SLO plane's
            # convention) — a percent here would clamp to max() and
            # hand the verdict to a single worst-case sample
            "p50_s": round(percentile(xs, 0.50), 6) if xs else None,
            "p99_s": round(percentile(xs, 0.99), 6) if xs else None,
        }
        total_stage = sum(stage_s[v].values())
        if total_stage > 0:
            stats[v]["stage_shares"] = {
                k: round(dur / total_stage, 4)
                for k, dur in sorted(stage_s[v].items())}
            stats[v]["dominant_stage"] = dominant_stage(stage_s[v])
    return stats


def compare(canary: dict, incumbent: dict, *, min_requests: int,
            max_p99_ratio: float, max_error_rate: float,
            golden_divergence: Optional[float] = None,
            golden_max: float = 0.5) -> Tuple[Optional[str], str]:
    """(verdict, reason) from two :func:`version_windows` rows plus an
    optional golden-probe divergence.  The golden probe outranks the
    latency windows — quality damage rolls back even when the canary
    is FAST — and insufficient traffic outranks everything."""
    if canary["requests"] < min_requests \
            or incumbent["requests"] < min_requests:
        return None, (
            f"insufficient traffic (canary {canary['requests']}, "
            f"incumbent {incumbent['requests']}, need {min_requests} "
            "each)")
    if golden_divergence is not None and golden_divergence > golden_max:
        return "rollback", (
            f"golden divergence {golden_divergence:.6g} > "
            f"{golden_max:.6g}")
    if canary["error_rate"] > max_error_rate \
            and canary["error_rate"] > incumbent["error_rate"]:
        return "rollback", (
            f"canary error rate {canary['error_rate']:.4f} > "
            f"{max_error_rate:.4f} (incumbent "
            f"{incumbent['error_rate']:.4f})")
    if canary["p99_s"] is not None and incumbent["p99_s"] is not None \
            and incumbent["p99_s"] > 0 \
            and canary["p99_s"] > max_p99_ratio * incumbent["p99_s"]:
        # the ledger's per-version breakdown names WHERE the canary
        # spends its extra latency — a rollback reason an operator can
        # act on, not just a ratio
        dom = canary.get("dominant_stage")
        where = f" (dominant stage: {dom})" if dom else ""
        return "rollback", (
            f"canary p99 {canary['p99_s']:.6f}s > {max_p99_ratio:g}x "
            f"incumbent p99 {incumbent['p99_s']:.6f}s{where}")
    return "promote", "canary held p99/error-rate vs incumbent"


def load_golden_set(path: str) -> List[dict]:
    """A golden set file is JSON: ``{"requests": [{"x": [...]}, ...]}``
    (or a bare list).  Raises on malformed content — a quality gate
    whose probe set silently failed to load is a gate that never
    fires."""
    with open(path) as f:
        doc = json.load(f)
    reqs = doc.get("requests") if isinstance(doc, dict) else doc
    if not isinstance(reqs, list) or not reqs:
        raise ValueError(f"golden set {path!r}: no requests")
    for i, r in enumerate(reqs):
        if not isinstance(r, dict) or "x" not in r:
            raise ValueError(f"golden set {path!r}: request #{i} has "
                             "no 'x'")
    return reqs


def golden_divergence(canary_ep: Endpoint, incumbent_ep: Endpoint,
                      requests: Sequence[dict],
                      timeout_s: float = 5.0) -> float:
    """Max absolute output divergence between the two versions over the
    fixed golden request set, probed DIRECTLY against one replica of
    each arm (bypassing the router: a probe must not perturb the
    per-version traffic windows it gates).  Probe failures raise — an
    unanswerable golden probe is evidence, not a skip."""
    worst = 0.0
    # probe ids must be FRESH per round: a reused id would hit the
    # replica's idempotency cache and replay an answer computed by an
    # OLDER weight version — masking the very divergence being probed
    nonce = time.monotonic_ns()
    for i, req in enumerate(requests):
        body = {"x": [float(v) for v in req["x"]]}
        ys = []
        for ep in (canary_ep, incumbent_ep):
            data = json.dumps(
                {"id": f"golden-{nonce}-{i}-{ep[1]}", **body}).encode()
            http_req = urllib.request.Request(
                f"http://{ep[0]}:{ep[1]}/infer", data=data,
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(http_req,
                                        timeout=timeout_s) as r:
                ys.append(json.loads(r.read())["y"])
        a, b = ys
        if len(a) != len(b):
            return float("inf")
        for va, vb in zip(a, b):
            worst = max(worst, abs(float(va) - float(vb)))
    return worst
