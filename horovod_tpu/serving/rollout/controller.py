"""The rollout controller: a governed train→serve transition.

State machine (docs/SERVING.md "Canary rollout")::

    idle ──begin()──▶ canary ──promote──▶ expanding ──promote──▶ promoted
                        │                     │
                        └──────rollback───────┴──▶ rolling_back ──▶ rolled_back

``begin(candidate, incumbent)`` pins a canary subset of fleet slots to
the candidate commit (heal pin = the INCUMBENT: a crashed canary's
replacement must shrink the canary, not re-grow it), pins the rest to
the incumbent (an unpinned replica would chase latest — which IS the
candidate — and silently widen the canary), and installs the router's
version split.  From there the controller only MEASURES:
``evaluate()`` reduces the stage's request-log window (plus the
optional golden-request quality probe) to a ``rollout_verdict``
finding, and the AUTOPILOT decides — the ``rollout-promote`` /
``rollout-rollback`` policies gate on the verdict and call back into
:meth:`_on_promote` / :meth:`_on_rollback` through the registered
action hooks.  In ``observe`` mode the decision stream shows exactly
what ``act`` would have done, and the rollout simply holds at its
current stage.

Every transition — begin, each verdict, each repin — continues ONE
trace id rooted at ``begin()`` (the finding carries the controller's
traceparent; the anomaly engine, the decision, and the action hooks
all child from it), so ``python -m horovod_tpu.diagnostics trace <id>``
prints the whole governed transition as a single causal tree.

Rollback leaves every slot PINNED to the incumbent: the poisoned
candidate is still the newest commit in the store, and an unpinned
replica would hot-swap right back into it.  Clearing the pins is the
operator's explicit decision (or the next ``begin()``'s).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from horovod_tpu import tracing
from horovod_tpu.common.config import env_float, env_int, env_str
from horovod_tpu.common.logging import get_logger
from horovod_tpu.metrics.anomaly import report_finding
from horovod_tpu.serving import metrics as smetrics
from horovod_tpu.serving.rollout import comparator

STATUS_FILE = "rollout_status.json"


def _flight(kind: str, **fields) -> None:
    try:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(kind, **fields)
    except Exception:
        pass


@dataclasses.dataclass
class RolloutConfig:
    """Knobs (KNOBS.md): env defaults, constructor overrides."""

    canary_pct: int = 25          # first-stage traffic share
    expand_pct: int = 50          # second stage before fleet-wide
    window_s: float = 5.0         # min seconds per evaluation window
    min_requests: int = 20        # per ARM before any verdict
    max_p99_ratio: float = 2.0    # canary p99 cap vs incumbent p99
    max_error_rate: float = 0.05  # canary error-rate cap
    golden_path: str = ""         # golden request set ("" = no probe)
    golden_max: float = 0.5       # max |y_canary - y_incumbent|

    @classmethod
    def from_env(cls) -> "RolloutConfig":
        return cls(
            canary_pct=env_int("ROLLOUT_CANARY_PCT", 25),
            expand_pct=env_int("ROLLOUT_EXPAND_PCT", 50),
            window_s=env_float("ROLLOUT_WINDOW_S", 5.0),
            min_requests=env_int("ROLLOUT_MIN_REQUESTS", 20),
            max_p99_ratio=env_float("ROLLOUT_MAX_P99_RATIO", 2.0),
            max_error_rate=env_float("ROLLOUT_MAX_ERROR_RATE", 0.05),
            golden_path=env_str("ROLLOUT_GOLDEN_SET", ""),
            golden_max=env_float("ROLLOUT_GOLDEN_MAX_DIVERGENCE", 0.5))


class RolloutController:
    """Drives one rollout at a time over a fleet + router pair.

    ``fleet`` needs the :class:`~horovod_tpu.serving.fleet.ReplicaFleet`
    rollout surface — ``slots()``, ``pin_slot()``, ``unpin_slot()``,
    ``endpoints_at(version)`` — so tests can substitute an in-process
    adapter.  ``router`` is the live :class:`Router` whose request log
    the comparator reads.
    """

    def __init__(self, fleet: Any, router: Any,
                 config: Optional[RolloutConfig] = None,
                 store_dir: Optional[str] = None) -> None:
        self.fleet = fleet
        self.router = router
        self.config = config or RolloutConfig.from_env()
        self.store_dir = store_dir
        self.state = "idle"
        self.rollout_id: Optional[str] = None
        self.candidate: Optional[int] = None
        self.incumbent: Optional[int] = None
        self.canary_slots: List[int] = []
        self.trace = None
        self.history: List[dict] = []  # transition audit
        self._seq = 0
        self._stage_started = 0.0
        self._stage_log_start = 0
        self._lock = threading.RLock()
        smetrics.set_rollout_state(self.state)

    # -- state machine -------------------------------------------------------
    def _set_state(self, state: str, **fields) -> None:
        prev = self.state
        self.state = state
        smetrics.set_rollout_state(state)
        smetrics.inc_rollout_transition(state)
        ctx = tracing.child(self.trace, "rollout")
        tracing.record_span("rollout", f"state:{state}", ctx,
                            start=time.time(), dur_s=0.0,
                            rollout=self.rollout_id,
                            prev=prev, **fields)
        self.history.append({"ts": round(time.time(), 3), "from": prev,
                             "to": state, **fields})
        _flight("rollout_transition", rollout=self.rollout_id,
                prev=prev, state=state, **fields)
        get_logger().warning("rollout %s: %s -> %s %s", self.rollout_id,
                             prev, state, fields or "")
        self._persist()

    def _new_stage(self) -> None:
        """Each traffic stage measures a FRESH window — evidence from
        a 25% canary must not leak into the 50% stage's verdict.  The
        anchor is the log's absolute sequence number, not a list
        index: the in-memory trim deletes head entries, and an index
        anchor would over-skip current-stage evidence after each
        trim."""
        self._stage_started = time.time()
        self._stage_log_start = self.router.log.seq_now()

    def begin(self, candidate: int, incumbent: int) -> dict:
        """Start a rollout: pin the canary subset to ``candidate``
        (healing at ``incumbent``), pin the rest to ``incumbent``, and
        split traffic.  Returns the initial status doc."""
        with self._lock:
            if self.state not in ("idle", "promoted", "rolled_back"):
                raise RuntimeError(
                    f"rollout already in progress (state={self.state})")
            self._seq += 1
            self.candidate = int(candidate)
            self.incumbent = int(incumbent)
            self.rollout_id = f"rollout-{self._seq}-v{candidate}"
            self.trace = tracing.new_trace("rollout")
            slots = list(self.fleet.slots())
            if len(slots) < 2:
                # the canary invariant is "at least 1, never the whole
                # fleet": a 1-slot fleet cannot keep an incumbent arm,
                # so there would be nothing to compare against and no
                # endpoint for the golden probe's incumbent side
                raise RuntimeError(
                    "rollout: need at least 2 live slots (canary + "
                    f"incumbent arm), have {len(slots)}")
            n_canary = max(1, round(len(slots)
                                    * self.config.canary_pct / 100.0))
            n_canary = min(n_canary, len(slots) - 1)
            self.canary_slots = slots[:n_canary]
            with tracing.activate(self.trace):
                for slot in slots:
                    if slot in self.canary_slots:
                        self.fleet.pin_slot(
                            slot, self.candidate, reason="pin",
                            heal_version=self.incumbent)
                    else:
                        # an unpinned replica chases latest — which IS
                        # the candidate: the incumbent arm must be
                        # pinned too or the canary silently widens
                        self.fleet.pin_slot(slot, self.incumbent,
                                            reason="pin")
                self._install_split(self.config.canary_pct)
            self._new_stage()
            self._set_state("canary", candidate=self.candidate,
                            incumbent=self.incumbent,
                            canary_slots=list(self.canary_slots),
                            pct=self.config.canary_pct)
            return self.status()

    def _install_split(self, pct: int) -> None:
        fleet, cand, inc = self.fleet, self.candidate, self.incumbent
        self.router.set_version_split(
            pct,
            lambda: fleet.endpoints_at(cand),
            lambda: fleet.endpoints_at(inc),
            canary_version=cand, incumbent_version=inc)

    # -- evaluation ----------------------------------------------------------
    def evaluate(self, force: bool = False) -> Optional[dict]:
        """Reduce the current stage's evidence to a ``rollout_verdict``
        finding (returned), or ``None`` when the stage window is still
        open / traffic is insufficient / no rollout is live.  The
        finding carries the rollout's traceparent, so the autopilot
        decision and action it triggers continue the SAME trace."""
        with self._lock:
            if self.state not in ("canary", "expanding"):
                return None
            if not force and time.time() - self._stage_started \
                    < self.config.window_s:
                return None
            entries = self.router.log.since(self._stage_log_start)
            stats = comparator.version_windows(
                entries, [self.candidate, self.incumbent])
            canary = stats[self.candidate]
            incumbent = stats[self.incumbent]
            golden = None
            if self.config.golden_path:
                golden = self._golden_probe()
            verdict, reason = comparator.compare(
                canary, incumbent,
                min_requests=self.config.min_requests,
                max_p99_ratio=self.config.max_p99_ratio,
                max_error_rate=self.config.max_error_rate,
                golden_divergence=golden,
                golden_max=self.config.golden_max)
            _flight("rollout_evaluate", rollout=self.rollout_id,
                    state=self.state, verdict=verdict, reason=reason,
                    golden_divergence=golden,
                    canary_requests=canary["requests"],
                    incumbent_requests=incumbent["requests"])
            if verdict is None:
                return None
            smetrics.inc_rollout_verdict(verdict)
            fields: Dict[str, Any] = {
                "verdict": verdict, "reason": reason,
                "rollout_id": self.rollout_id,
                "candidate": self.candidate,
                "incumbent": self.incumbent,
                "state": self.state,
                "canary_stats": canary, "incumbent_stats": incumbent}
            if golden is not None:
                fields["golden_divergence"] = round(golden, 6)
            if self.trace is not None:
                fields[tracing.TRACEPARENT] = self.trace.traceparent
            return report_finding("rollout_verdict", **fields)

    def _golden_probe(self) -> Optional[float]:
        """Max output divergence candidate vs incumbent on the golden
        set; ``inf`` when the probe itself fails (an unanswerable
        canary is rollback evidence, not a skip)."""
        canary_eps = self.fleet.endpoints_at(self.candidate)
        incumbent_eps = self.fleet.endpoints_at(self.incumbent)
        if not canary_eps or not incumbent_eps:
            return None  # mid-heal: no arm to probe yet
        try:
            requests = comparator.load_golden_set(self.config.golden_path)
            return comparator.golden_divergence(
                canary_eps[0], incumbent_eps[0], requests)
        except Exception:
            get_logger().warning(
                "rollout %s: golden probe failed — counting it as "
                "divergence", self.rollout_id, exc_info=True)
            return float("inf")

    # -- autopilot action hooks ---------------------------------------------
    def register_autopilot_hooks(self) -> "RolloutController":
        """Wire this controller as the promote/rollback remediation
        target (the serving analog of
        ``ReplicaFleet.register_autopilot_hook``)."""
        from horovod_tpu.autopilot import actions
        actions.register_promote_rollout_hook(self._on_promote)
        actions.register_rollback_rollout_hook(self._on_rollback)
        return self

    def _on_promote(self, finding: dict) -> None:
        with self._lock:
            if finding.get("rollout_id") not in (None, self.rollout_id):
                return  # a stale finding from a previous rollout
            if self.state == "canary":
                self._install_split(self.config.expand_pct)
                self._new_stage()
                self._set_state("expanding",
                                pct=self.config.expand_pct)
            elif self.state == "expanding":
                # fleet-wide: flip every slot to the candidate, then
                # unpin — chase-latest and the candidate now agree
                for slot in self.fleet.slots():
                    self.fleet.pin_slot(slot, self.candidate,
                                        reason="pin")
                    self.fleet.unpin_slot(slot)
                self.router.clear_version_split()
                self.canary_slots = []
                self._set_state("promoted", version=self.candidate)

    def _on_rollback(self, finding: dict) -> None:
        with self._lock:
            if finding.get("rollout_id") not in (None, self.rollout_id):
                return
            if self.state not in ("canary", "expanding"):
                return
            self._set_state("rolling_back",
                            reason=finding.get("reason"))
            # EVERY slot ends pinned to the incumbent — the poisoned
            # candidate is still the newest commit in the store, and
            # an unpinned replica would hot-swap right back into it.
            # The repin is the same atomic between-batch flip as a hot
            # swap: in-flight requests finish on the version that
            # computed them, zero requests fail
            for slot in self.fleet.slots():
                self.fleet.pin_slot(slot, self.incumbent,
                                    reason="rollback")
            self.router.clear_version_split()
            self.canary_slots = []
            self._set_state("rolled_back", version=self.incumbent)

    def rollback(self, reason: str = "manual") -> None:
        """Operator escape hatch (docs/SERVING.md "Canary rollout"
        runbook): force the rollback path without waiting for a
        verdict.  Idempotent — a no-op outside canary/expanding."""
        self._on_rollback({"rollout_id": self.rollout_id,
                           "reason": reason})

    # -- status --------------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            split = None
            try:
                split = self.router.version_split()
            except Exception:
                pass
            doc = {
                "rollout_id": self.rollout_id,
                "state": self.state,
                "candidate": self.candidate,
                "incumbent": self.incumbent,
                "canary_slots": list(self.canary_slots),
                "split": split,
                "history": list(self.history),
                "updated_at": round(time.time(), 3),
            }
            if self.trace is not None:
                doc["trace"] = self.trace.trace_id
            return doc

    def _persist(self) -> None:
        """Durable status (atomic rename) so ``python -m
        horovod_tpu.serving rollout status`` answers from OUTSIDE the
        controller process — the stuck-rollout runbook's first stop."""
        if not self.store_dir:
            return
        try:
            path = os.path.join(self.store_dir, STATUS_FILE)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.status(), f, indent=1)
            os.replace(tmp, path)
        except OSError:
            get_logger().warning("rollout: status persist failed",
                                 exc_info=True)


def read_status(store_dir: str) -> Optional[dict]:
    """The persisted status doc, or ``None`` when no rollout ever ran
    against this store."""
    try:
        with open(os.path.join(store_dir, STATUS_FILE)) as f:
            return json.load(f)
    except OSError:
        return None
    except ValueError:
        return None
