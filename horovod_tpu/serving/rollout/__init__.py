"""Canary weight rollout: autopilot-governed train→serve promotion
(docs/SERVING.md "Canary rollout").

The missing half of the continuous loop: training commits durably
(:mod:`horovod_tpu.checkpoint`), replicas hot-swap from the store
(:mod:`horovod_tpu.serving.replica`) — but an ungoverned swap puts a
poisoned commit on 100% of traffic before anyone measures it.  This
package turns "step N committed" into a governed transition:

* :class:`RolloutController` pins a canary subset of the fleet to the
  candidate version, splits traffic by weight version through the
  router, and reduces per-version request-log windows (plus an
  optional golden-request quality probe) to a ``rollout_verdict``
  finding.
* The autopilot's ``rollout-promote`` / ``rollout-rollback`` policies
  (:func:`horovod_tpu.autopilot.policy.default_policies`) gate on the
  verdict and drive the controller's promote/rollback hooks — canary →
  50% → fleet-wide, or an atomic repin of every canary replica back to
  the incumbent with zero failed requests.
* One trace id covers the whole transition; ``python -m
  horovod_tpu.diagnostics trace <id>`` prints the causal tree.
"""

from horovod_tpu.serving.rollout.comparator import (  # noqa: F401
    compare,
    golden_divergence,
    load_golden_set,
    version_windows,
)
from horovod_tpu.serving.rollout.controller import (  # noqa: F401
    RolloutConfig,
    RolloutController,
    read_status,
)

__all__ = [
    "RolloutConfig",
    "RolloutController",
    "compare",
    "golden_divergence",
    "load_golden_set",
    "read_status",
    "version_windows",
]
