"""Slot scheduler: sequences join and leave the static decode batch
only at step boundaries.

The compiled decode step runs over a FIXED array of ``n_slots`` slots;
which sequences occupy them is host-side bookkeeping that changes
between steps, never inside one.  This module owns that bookkeeping:

* a FIFO waiting line fed by the admission queue (the replica's
  :class:`~horovod_tpu.serving.batcher.DynamicBatcher` — bounded,
  explicit 429 sheds, drain semantics);
* admission: a waiting request takes a free slot only when the page
  pool can cover its WORST CASE (``prompt + max_new`` tokens) — a slot
  can be free while pages are scarce, and then the request keeps
  waiting rather than risking a mid-decode out-of-pages;
* prefill chunking: an admitted request's prompt is cut into
  ``prefill_chunk``-token chunks the engine runs one per engine
  iteration, so one long prompt never stalls the live decode batch;
* eviction at finish/deadline/error: the slot and its pages return to
  the pool the same step boundary the sequence leaves.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from horovod_tpu.serving.generate.pages import PagePool

#: GenRequest lifecycle states
WAITING, PREFILL, DECODE, DONE = "waiting", "prefill", "decode", "done"


class GenRequest:
    """One generation request riding through the engine.

    ``tokens`` grows INCREMENTALLY as decode steps emit (callers may
    observe it mid-flight; ``on_token`` fires per emission for true
    streaming consumers); the terminal result/error is delivered
    through the admission queue's :class:`PendingRequest` the replica
    handler blocks on."""

    __slots__ = ("id", "prompt", "max_new", "state", "slot", "pages",
                 "prefill_pos", "tokens", "submitted_at", "admitted_at",
                 "first_token_at", "last_token_at", "prefill_chunks",
                 "decode_steps", "trace", "pending", "on_token",
                 "finish_reason", "wait_mark", "slot_wait_s",
                 "page_wait_s", "prefill_s", "decode_s", "swap_pause_s")

    def __init__(self, req_id: str, prompt, max_new: int,
                 trace=None, on_token=None) -> None:
        self.id = req_id
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.state = WAITING
        self.slot: Optional[int] = None
        self.pages: List[int] = []
        self.prefill_pos = 0          # prompt tokens already prefilled
        self.tokens: List[int] = []   # emitted tokens, grows per step
        self.submitted_at = time.monotonic()
        self.admitted_at = 0.0
        self.first_token_at = 0.0
        self.last_token_at = 0.0
        self.prefill_chunks = 0
        self.decode_steps = 0
        self.trace = trace
        self.pending = None           # admission-queue PendingRequest
        self.on_token = on_token
        self.finish_reason: Optional[str] = None
        # request-ledger stage accounting (docs/OBSERVABILITY.md
        # "Serving request ledger"): waiting time split by WHY the line
        # was blocked (free-slot scarcity vs page-pool scarcity), plus
        # wall seconds inside prefill/decode and weight-swap pauses
        self.wait_mark = self.submitted_at
        self.slot_wait_s = 0.0
        self.page_wait_s = 0.0
        self.prefill_s = 0.0
        self.decode_s = 0.0
        self.swap_pause_s = 0.0

    def stages(self) -> dict:
        """The generate-plane slice of this request's stage ledger."""
        return {"slot_wait": self.slot_wait_s,
                "page_wait": self.page_wait_s,
                "prefill": self.prefill_s,
                "decode": self.decode_s,
                "swap_pause": self.swap_pause_s}

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def worst_case_tokens(self) -> int:
        return self.prompt_len + self.max_new

    def emit(self, token: int, now: float) -> None:
        self.tokens.append(int(token))
        if not self.first_token_at:
            self.first_token_at = now
        self.last_token_at = now
        if self.on_token is not None:
            try:
                self.on_token(int(token))
            except Exception:
                pass  # a slow/broken stream consumer must not stall decode


class SlotScheduler:
    """Admission order, eviction, and prefill chunking over the static
    slot array.  Thread-safe; every mutation happens at an engine step
    boundary (the engine loop is the only caller of admit/evict)."""

    def __init__(self, n_slots: int, pool: PagePool,
                 prefill_chunk: int, max_ctx: int) -> None:
        assert n_slots >= 1 and prefill_chunk >= 1
        self.n_slots = int(n_slots)
        self.pool = pool
        self.prefill_chunk = int(prefill_chunk)
        self.max_ctx = int(max_ctx)
        self._lock = threading.Lock()
        self._waiting: Deque[GenRequest] = deque()
        self.slots: List[Optional[GenRequest]] = [None] * self.n_slots
        # what blocked the LAST admission pass: "slot" (no free slot)
        # or "page" (pool can't cover the head's worst case) — the
        # request ledger charges waiting time since that pass to the
        # matching stage (slot_wait vs page_wait), which is exactly the
        # discrimination the kv_thrash detector runs on
        self._block_cause: Optional[str] = None

    # -- intake -------------------------------------------------------------
    def add_waiting(self, req: GenRequest) -> None:
        with self._lock:
            self._waiting.append(req)

    def waiting_count(self) -> int:
        with self._lock:
            return len(self._waiting)

    # -- admission ----------------------------------------------------------
    def admit(self) -> List[GenRequest]:
        """Move waiting requests into free slots, FIFO, page-gated.
        The head of the line blocks the line: skipping a big request to
        admit a later small one would starve it forever under load.
        Returns the newly admitted requests (state=PREFILL, pages
        allocated, slot assigned)."""
        admitted: List[GenRequest] = []
        now = time.monotonic()
        with self._lock:
            # settle waiting time accrued since the previous pass under
            # the cause that blocked it (default: slot — queue transit
            # before the first classification is batch-join wait)
            cause = self._block_cause
            for r in self._waiting:
                dt = max(0.0, now - r.wait_mark)
                r.wait_mark = now
                if cause == "page":
                    r.page_wait_s += dt
                else:
                    r.slot_wait_s += dt
            self._block_cause = None
            while self._waiting:
                free = [i for i, r in enumerate(self.slots) if r is None]
                if not free:
                    self._block_cause = "slot"
                    break
                req = self._waiting[0]
                pages = self.pool.alloc(
                    self.pool.plan.pages_for(req.worst_case_tokens))
                if pages is None:
                    # pool can't cover the head yet; keep FIFO
                    self._block_cause = "page"
                    break
                self._waiting.popleft()
                req.slot = free[0]
                req.pages = pages
                req.state = PREFILL
                req.admitted_at = now
                self.slots[free[0]] = req
                admitted.append(req)
        return admitted

    # -- prefill chunking ---------------------------------------------------
    def next_prefill_chunk(self, req: GenRequest) \
            -> Optional[Tuple[int, int]]:
        """The next (start, length) chunk of ``req``'s prompt still to
        prefill, or None when prefill is complete.  Chunks are at most
        ``prefill_chunk`` tokens; the engine runs ONE per iteration per
        sequence so prefill interleaves with live decode steps."""
        if req.prefill_pos >= req.prompt_len:
            return None
        start = req.prefill_pos
        return start, min(self.prefill_chunk, req.prompt_len - start)

    def chunks_for(self, prompt_len: int) -> int:
        return max(1, -(-int(prompt_len) // self.prefill_chunk))

    # -- views --------------------------------------------------------------
    def prefilling(self) -> List[GenRequest]:
        with self._lock:
            return [r for r in self.slots
                    if r is not None and r.state == PREFILL]

    def decoding(self) -> List[GenRequest]:
        with self._lock:
            return [r for r in self.slots
                    if r is not None and r.state == DECODE]

    def occupied(self) -> int:
        with self._lock:
            return sum(r is not None for r in self.slots)

    def busy(self) -> bool:
        with self._lock:
            return bool(self._waiting) or \
                any(r is not None for r in self.slots)

    # -- eviction -----------------------------------------------------------
    def evict(self, req: GenRequest, reason: str) -> None:
        """Return the slot and pages at a step boundary; terminal state
        delivery (set_result/set_error) is the engine's job."""
        with self._lock:
            if req.slot is not None \
                    and self.slots[req.slot] is req:
                self.slots[req.slot] = None
            req.state = DONE
            req.finish_reason = reason
            pages, req.pages = req.pages, []
        self.pool.free(pages)

    def drop_waiting(self, req: GenRequest) -> bool:
        """Remove a never-admitted request (deadline expired while
        waiting).  True when it was still in the line."""
        with self._lock:
            try:
                self._waiting.remove(req)
                return True
            except ValueError:
                return False
