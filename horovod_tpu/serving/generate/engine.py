"""The generative decode engine: ONE jit'd fixed-shape decode step over
a static slot array, fed by continuous token-level batching.

The request-level serving loop (replica.py) answers a whole request per
forward; autoregressive decode breaks that granularity — sequences
finish at different times, and a request-level batch strands chip time
on every early finisher.  This engine decodes at TOKEN granularity:

* a static array of ``HVD_TPU_GEN_SLOTS`` decode slots; the compiled
  step (:func:`~horovod_tpu.models.transformer.decode_step_paged`)
  always runs over all of them, with an active mask — membership churn
  is host bookkeeping between steps and NEVER changes a compiled shape
  (the compile-stability guard in tests/test_generate.py asserts
  exactly one decode-step compile under heavy join/leave churn);
* K/V history lives in the paged pool (:mod:`.pages`): admission
  allocates a request's WORST-CASE pages up front, eviction returns
  them the same step boundary the sequence leaves;
* prompts prefill in fixed ``HVD_TPU_PREFILL_CHUNK``-token chunks, one
  chunk per engine iteration per sequence, interleaved with live
  decode steps — a long prompt never stalls the decode batch
  (prefill/decode split);
* the admission edge is the SAME bounded
  :class:`~horovod_tpu.serving.batcher.DynamicBatcher` contract as
  request-level serving (explicit 429 sheds, drain semantics), run
  with ``max_wait_s=0`` — holding a batch window open would stall the
  decode loop for nothing, the slot scheduler IS the batching.

Every request's path is traced (submit→admit→prefill→each decode
step→finish, PR-15 spans) and metered per phase
(``hvd_serving_prefill/decode_seconds_total``, slot occupancy, page
pool, TTFT/ITL — docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.common.config import env_int
from horovod_tpu.common.logging import get_logger
from horovod_tpu.serving import metrics as smetrics
from horovod_tpu.serving.batcher import DeadlineError, DynamicBatcher
from horovod_tpu.serving.generate.pages import PagePool, plan_kv_pages
from horovod_tpu.serving.generate.scheduler import (DECODE, DONE, PREFILL,
                                                    GenRequest,
                                                    SlotScheduler)


def _jit_step_fns(cfg) -> Tuple[Callable, Callable]:
    """The two compiled entry points, as NAMED module-visible closures:
    compile_watch attributes compiles by function name, and the
    one-compile guarantee is asserted against ``gen_decode_step``."""
    import jax

    from horovod_tpu.models.transformer import (decode_step_paged,
                                                prefill_chunk_paged)

    def gen_decode_step(params, k_pages, v_pages, page_table, lengths,
                        last_token, active):
        return decode_step_paged(params, k_pages, v_pages, page_table,
                                 lengths, last_token, active, cfg)

    def gen_prefill_chunk(params, k_pages, v_pages, page_row, tokens,
                          pos0, valid):
        return prefill_chunk_paged(params, k_pages, v_pages, page_row,
                                   tokens, pos0, valid, cfg)

    return jax.jit(gen_decode_step), jax.jit(gen_prefill_chunk)


class GenerateEngine:
    """Continuous-batching decode engine over one model's weights.

    Thread model: :meth:`submit` runs on any thread (handler threads —
    it only touches the bounded admission queue); ALL slot/page/array
    mutation happens in :meth:`step_once`, called either by the
    background loop (:meth:`start`) or directly by tests/bench drivers
    for deterministic single-threaded stepping.
    """

    def __init__(self, params: Any, cfg,
                 n_slots: Optional[int] = None,
                 page_bytes: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_ctx: Optional[int] = None,
                 batcher: Optional[DynamicBatcher] = None) -> None:
        import jax.numpy as jnp

        from horovod_tpu.models.transformer import (flatten_decode_params,
                                                    kv_cache_spec)
        self.cfg = cfg
        self.n_slots = int(n_slots or env_int("GEN_SLOTS", 4))
        self.prefill_chunk = int(prefill_chunk
                                 or env_int("PREFILL_CHUNK", 16))
        self.max_ctx = int(max_ctx or cfg.max_seq)
        n_layers, kv_width, kv_dtype = kv_cache_spec(cfg)
        self.plan = plan_kv_pages(n_layers, kv_width, kv_dtype,
                                  self.n_slots, self.max_ctx, page_bytes)
        self.pool = PagePool(self.plan)
        self.scheduler = SlotScheduler(self.n_slots, self.pool,
                                       self.prefill_chunk, self.max_ctx)
        # max_wait_s=0: the window must close instantly — the slot
        # scheduler is the batching, the queue is only admission control
        self.batcher = batcher or DynamicBatcher(
            max_batch_size=self.n_slots, max_wait_s=0.0)
        self.params = flatten_decode_params(params)
        self._decode_fn, self._prefill_fn = _jit_step_fns(cfg)
        shape = (n_layers, self.plan.total_pages + 1,
                 self.plan.page_tokens, kv_width)
        self._k_pages = jnp.zeros(shape, jnp.float32)
        self._v_pages = jnp.zeros(shape, jnp.float32)
        # host mirrors of the decode step's per-slot inputs; rows of
        # the page table default to the scratch page id
        self._page_table = np.full(
            (self.n_slots, self.plan.pages_per_slot),
            self.plan.total_pages, dtype=np.int32)
        self._lengths = np.zeros((self.n_slots,), np.int32)
        self._last_token = np.zeros((self.n_slots,), np.int32)
        self._active = np.zeros((self.n_slots,), bool)
        self.decode_steps_total = 0
        self.prefill_chunks_total = 0
        # weight-swap pause gate: the replica's hot swap clears it
        # around the params flip so the decode loop holds at a step
        # boundary; the held time is charged to every live sequence's
        # ``swap_pause`` ledger stage
        self._swap_gate = threading.Event()
        self._swap_gate.set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- limits -------------------------------------------------------------
    @property
    def max_request_tokens(self) -> int:
        """Hard per-request bound: prompt + max_new must fit one slot's
        page table AND the model context."""
        return min(self.max_ctx, self.plan.slot_tokens)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "GenerateEngine":
        self._thread = threading.Thread(target=self._run,
                                        name="hvd-gen-engine",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.step_once(idle_wait_s=0.05)

    # -- intake -------------------------------------------------------------
    def submit(self, req_id: str, prompt, max_new: int,
               deadline_s: Optional[float] = None, trace=None,
               on_token=None) -> GenRequest:
        """Admit one generation request (any thread).  Raises
        :class:`~horovod_tpu.serving.batcher.SheddedError` /
        :class:`~horovod_tpu.serving.batcher.DrainingError` exactly like
        request-level admission, and :class:`ValueError` when the worst
        case cannot fit a slot.  The caller blocks on
        ``req.pending.wait()`` for the terminal result."""
        req = GenRequest(req_id, prompt, int(max_new), trace=trace,
                         on_token=on_token)
        if req.max_new < 1:
            raise ValueError(f"request {req_id}: max_new must be >= 1")
        if req.prompt_len < 1:
            raise ValueError(f"request {req_id}: empty prompt")
        if req.worst_case_tokens > self.max_request_tokens:
            raise ValueError(
                f"request {req_id}: prompt+max_new "
                f"({req.worst_case_tokens}) exceeds the per-slot "
                f"capacity ({self.max_request_tokens})")
        req.pending = self.batcher.submit(req_id, req,
                                          deadline_s=deadline_s)
        return req

    def generate(self, prompt, max_new: int, req_id: str = "local",
                 deadline_s: Optional[float] = None) -> dict:
        """Blocking convenience wrapper (the engine loop must be
        running, or another thread stepping)."""
        req = self.submit(req_id, prompt, max_new, deadline_s=deadline_s)
        wait_s = (req.pending.deadline - time.monotonic()) + 1.0
        return req.pending.wait(timeout=max(wait_s, 0.1))

    # -- drain --------------------------------------------------------------
    def drain(self) -> None:
        self.batcher.drain()

    def drained(self) -> bool:
        """Admission stopped AND every admitted sequence answered."""
        return self.batcher.draining and self.batcher.drained() \
            and not self.scheduler.busy()

    def wait_drained(self, timeout_s: float = 30.0) -> bool:
        end = time.monotonic() + timeout_s
        while not self.drained():
            if time.monotonic() >= end:
                return False
            time.sleep(0.01)
        return True

    # -- weight-swap pause --------------------------------------------------
    def begin_swap(self) -> None:
        """Hold the decode loop at the next step boundary (the replica's
        hot weight swap brackets the params flip with begin/end)."""
        self._swap_gate.clear()

    def end_swap(self) -> None:
        self._swap_gate.set()

    def _swap_wait(self) -> None:
        if self._swap_gate.is_set():
            return
        t0 = time.monotonic()
        self._swap_gate.wait()
        pause = time.monotonic() - t0
        if pause <= 0:
            return
        # charge the pause to every LIVE sequence's ledger (waiting
        # requests keep accruing slot/page wait through the scheduler)
        for req in list(self.scheduler.slots):
            if req is not None:
                req.swap_pause_s += pause

    # -- the step -----------------------------------------------------------
    def step_once(self, idle_wait_s: float = 0.0) -> bool:
        """One engine iteration: pull admissions, sweep deadlines,
        admit into slots, ONE prefill chunk per prefilling sequence,
        ONE batched decode step, deliver finishes.  Returns True when
        any work happened."""
        self._swap_wait()
        pulled = self._pull_admissions(idle_wait_s)
        self._sweep_deadlines()
        admitted = self.scheduler.admit()
        for req in admitted:
            self._on_admitted(req)
        worked = pulled or bool(admitted)
        worked = self._prefill_tick() or worked
        worked = self._decode_tick() or worked
        smetrics.set_slot_occupancy(self.scheduler.occupied(),
                                    self.n_slots)
        smetrics.set_gen_waiting(self.scheduler.waiting_count())
        return worked

    def _pull_admissions(self, idle_wait_s: float) -> bool:
        # when slots/queue hold live work the pull must not block; only
        # a fully idle engine waits in next_batch
        timeout = 0.0 if self.scheduler.busy() else float(idle_wait_s)
        batch = self.batcher.next_batch(timeout_s=timeout)
        if not batch:
            return False
        for pending in batch:
            req: GenRequest = pending.payload
            req.pending = pending
            self.scheduler.add_waiting(req)
        # the queue's job ends at hand-off; sequence lifetime is the
        # scheduler's (drain completion = drained() above, which also
        # requires the scheduler to be empty)
        self.batcher.batch_done()
        return True

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        for req in list(self.scheduler.slots):
            if req is None or req.pending is None:
                continue
            if req.pending.deadline <= now:
                smetrics.inc_shed("deadline")
                self._finish(req, "deadline", error=DeadlineError(
                    f"request {req.id}: deadline expired mid-generation "
                    f"after {len(req.tokens)} tokens"))

    def _on_admitted(self, req: GenRequest) -> None:
        row = self._page_table[req.slot]
        row[:] = self.plan.total_pages          # scratch-fill the tail
        row[:len(req.pages)] = req.pages
        self._lengths[req.slot] = 0
        self._last_token[req.slot] = 0
        self._active[req.slot] = False          # active only once decoding
        self._span(req, "gen_admit",
                   dur_s=req.admitted_at - req.submitted_at,
                   slot=req.slot, pages=len(req.pages),
                   queued_s=round(req.admitted_at - req.submitted_at, 6))

    # -- prefill ------------------------------------------------------------
    def _prefill_tick(self) -> bool:
        import jax.numpy as jnp
        worked = False
        for req in self.scheduler.prefilling():
            chunk = self.scheduler.next_prefill_chunk(req)
            if chunk is None:     # defensive; PREFILL implies a chunk
                continue
            start, length = chunk
            tokens = np.zeros((self.prefill_chunk,), np.int32)
            tokens[:length] = req.prompt[start:start + length]
            t0 = time.monotonic()
            nxt, self._k_pages, self._v_pages = self._prefill_fn(
                self.params, self._k_pages, self._v_pages,
                jnp.asarray(self._page_table[req.slot]),
                jnp.asarray(tokens), np.int32(start), np.int32(length))
            nxt = int(nxt)
            dur = time.monotonic() - t0
            smetrics.observe_prefill(dur)
            self.prefill_chunks_total += 1
            req.prefill_s += dur
            req.prefill_pos += length
            req.prefill_chunks += 1
            self._span(req, "gen_prefill", dur_s=dur,
                       chunk=req.prefill_chunks, chunk_start=start,
                       tokens=length)
            worked = True
            if req.prefill_pos >= req.prompt_len:
                # the last chunk's last valid logits ARE the first
                # emitted token: prefill ends with TTFT, decode
                # continues from it
                req.state = DECODE
                self._lengths[req.slot] = req.prompt_len
                self._last_token[req.slot] = nxt
                self._active[req.slot] = True
                self._emit(req, nxt)
                smetrics.count_gen_tokens(1)
                smetrics.observe_ttft(
                    req.first_token_at - req.submitted_at)
                if len(req.tokens) >= req.max_new:
                    self._finish(req, "length")
        return worked

    # -- decode -------------------------------------------------------------
    def _decode_tick(self) -> bool:
        import jax.numpy as jnp
        decoding = self.scheduler.decoding()
        if not decoding:
            return False
        t0 = time.monotonic()
        nxt, self._k_pages, self._v_pages = self._decode_fn(
            self.params, self._k_pages, self._v_pages,
            jnp.asarray(self._page_table), jnp.asarray(self._lengths),
            jnp.asarray(self._last_token), jnp.asarray(self._active))
        nxt = np.asarray(nxt)
        dur = time.monotonic() - t0
        self.decode_steps_total += 1
        for req in decoding:
            s = req.slot
            tok = int(nxt[s])
            req.decode_steps += 1
            req.decode_s += dur  # each rider experiences the full step
            self._lengths[s] += 1
            self._last_token[s] = tok
            self._emit(req, tok)
            self._span(req, "gen_decode_step", dur_s=dur,
                       step=req.decode_steps, token=tok,
                       batch=len(decoding))
            if len(req.tokens) >= req.max_new:
                self._finish(req, "length")
        smetrics.observe_decode(dur, len(decoding))
        smetrics.observe_batch(len(decoding), top=self.n_slots)
        return True

    # -- delivery -----------------------------------------------------------
    def _emit(self, req: GenRequest, token: int) -> None:
        now = time.monotonic()
        prev = req.last_token_at
        req.emit(token, now)
        if prev:
            smetrics.observe_itl(now - prev)

    def _finish(self, req: GenRequest, reason: str,
                error: Optional[BaseException] = None) -> None:
        s = req.slot
        self.scheduler.evict(req, reason)
        if s is not None:
            self._active[s] = False
            self._lengths[s] = 0
            self._last_token[s] = 0
            self._page_table[s, :] = self.plan.total_pages
        smetrics.inc_gen_finished(reason)
        now = time.monotonic()
        stages = {k: round(v, 6) for k, v in req.stages().items()}
        self._span(req, "gen_finish",
                   dur_s=now - req.submitted_at, reason=reason,
                   tokens_emitted=len(req.tokens),
                   prefill_chunks=req.prefill_chunks,
                   decode_steps=req.decode_steps,
                   ttft_s=round((req.first_token_at - req.submitted_at)
                                if req.first_token_at else 0.0, 6),
                   **{f"stage_{k}": v for k, v in stages.items()
                      if v > 0})
        if req.pending is None:
            return
        if error is not None:
            req.pending.set_error(error)
            return
        ttft = (req.first_token_at - req.submitted_at) \
            if req.first_token_at else 0.0
        req.pending.set_result({
            "tokens": list(req.tokens),
            "tokens_emitted": len(req.tokens),
            "finish_reason": reason,
            "prompt_tokens": req.prompt_len,
            "prefill_chunks": req.prefill_chunks,
            "decode_steps": req.decode_steps,
            "ttft_s": round(ttft, 6),
            "total_s": round(now - req.submitted_at, 6),
            # the generate-plane slice of the request ledger — the
            # replica handler adds its own stages and the router closes
            # the books (docs/OBSERVABILITY.md "Serving request ledger")
            "stages": stages,
        })

    def _span(self, req: GenRequest, name: str, dur_s: float,
              **attrs) -> None:
        if req.trace is None:
            return
        try:
            from horovod_tpu import tracing
            tracing.record_span(
                "serving", name, tracing.child(req.trace, "serving"),
                start=time.time() - max(dur_s, 0.0), dur_s=dur_s,
                request=req.id, **attrs)
        except Exception:
            pass  # tracing must never take down the decode loop


# -- request-level baseline ---------------------------------------------------
def request_level_generate(engine: GenerateEngine,
                           requests: Sequence[Tuple[Any, int]],
                           traced: bool = False,
                           on_token_factory: Optional[Callable] = None
                           ) -> List[GenRequest]:
    """The request-granular discipline the continuous engine replaces,
    driven through the SAME compiled step functions so the comparison
    is apples-to-apples: admit a full gang of ``n_slots`` requests,
    decode until the gang's LONGEST sequence finishes — early
    finishers strand their slot — and only then admit the next gang.

    ``traced``/``on_token_factory`` attach the SAME per-request
    instrumentation the bench puts on the continuous run (a trace
    context per request, an ``on_token_factory(i)`` callback per
    request) so neither side wins on untracked overhead.

    The engine must NOT be running its background loop.  Returns the
    finished :class:`GenRequest` objects in submission order; compare
    ``engine.decode_steps_total`` deltas (and wall time) against a
    continuous run of the same request set."""
    if engine._thread is not None and engine._thread.is_alive():
        raise RuntimeError("baseline needs exclusive manual stepping")

    def _trace():
        if not traced:
            return None
        from horovod_tpu import tracing
        return tracing.new_trace("serving")

    reqs = [GenRequest(f"gang-{i}", prompt, int(max_new), trace=_trace(),
                       on_token=(on_token_factory(i)
                                 if on_token_factory else None))
            for i, (prompt, max_new) in enumerate(requests)]
    for lo in range(0, len(reqs), engine.n_slots):
        gang = reqs[lo:lo + engine.n_slots]
        for r in gang:
            engine.scheduler.add_waiting(r)
        guard = 0
        while any(r.state != DONE for r in gang):
            engine.step_once()
            guard += 1
            if guard > 100_000:
                raise RuntimeError("baseline failed to converge")
    return reqs


# -- demo model ---------------------------------------------------------------
def demo_gen_setup(vocab: int = 64, d_model: int = 32, n_layers: int = 2,
                   n_heads: int = 2, max_seq: int = 64,
                   seed: int = 0) -> Tuple[Any, Any]:
    """A deterministic tiny dense transformer — the generate-mode
    analog of :func:`~horovod_tpu.serving.replica.demo_params`.
    Returns ``(params, cfg)`` sized for the CPU test mesh; fp32 so the
    token-parity contract is exact."""
    import jax.numpy as jnp

    from horovod_tpu.models.transformer import (TransformerConfig,
                                                init_params)
    cfg = TransformerConfig(vocab_size=vocab, d_model=d_model,
                            n_heads=n_heads, n_layers=n_layers,
                            d_ff=2 * d_model, max_seq=max_seq,
                            n_experts=0, dtype=jnp.float32,
                            param_dtype=jnp.float32, remat=False)
    params = init_params(np.random.RandomState(seed), cfg, n_stages=1)
    return params, cfg
