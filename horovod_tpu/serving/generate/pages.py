"""Paged KV-cache pool: byte-budgeted pages for the generative engine.

The decode engine never materializes one monolithic ``[slot, max_seq]``
KV tensor per sequence.  Instead the cache is a POOL of fixed-size
pages — block-granular chunks of ``page_tokens`` tokens each — and
every slot owns an ordered page table mapping its token positions onto
pool pages.  Sizing transplants the gradient-bucket idiom from
:mod:`horovod_tpu.train.buckets`: the page byte budget resolves
explicit-arg > ``HVD_TPU_KV_PAGE_BYTES`` > a floor derived from the
engine's fusion threshold (the same "one unit of memory traffic"
number the bucket planner falls back to, capped so a page stays a
block, not a buffer), and the plan is pure metadata cached per model
fingerprint (layers/width/dtype/slots/context — an
``functools.lru_cache`` exactly like ``_plan_cached``).

The pool itself is host-side bookkeeping only (a free list + per-slot
ownership); the actual page ARRAYS live in the engine as fixed-shape
jax buffers ``[L, total_pages+1, page_tokens, kv_width]`` — the +1 row
is the scratch page inactive slots write into so membership churn
never changes the compiled shape.  Allocation happens ONLY at
decode-step boundaries (admission/eviction), so the compiled decode
step sees a constant-shape page table every call.
"""

from __future__ import annotations

import functools
import threading
from typing import List, NamedTuple, Optional

import numpy as np

from horovod_tpu.common.config import env_int

#: cap on the fusion-threshold fallback: a KV page is a block (tokens of
#: one sequence), not a 64 MiB comm buffer
DEFAULT_PAGE_BYTES_CAP = 64 * 1024


def resolve_page_bytes(page_bytes: Optional[int] = None) -> int:
    """Effective page byte budget: explicit argument >
    ``HVD_TPU_KV_PAGE_BYTES`` > the bucket planner's fallback chain
    (``resolve_bucket_bytes``) capped at :data:`DEFAULT_PAGE_BYTES_CAP`."""
    if page_bytes is not None:
        return max(1, int(page_bytes))
    env = env_int("KV_PAGE_BYTES", 0)
    if env > 0:
        return env
    from horovod_tpu.train.buckets import resolve_bucket_bytes
    return max(1, min(resolve_bucket_bytes(), DEFAULT_PAGE_BYTES_CAP))


class KVPagePlan(NamedTuple):
    """One model's paged-cache geometry (pure metadata, no arrays).

    ``page_tokens`` tokens fit one page under the byte budget (a page
    holds K AND V for every layer at those positions — the whole
    per-token cache footprint, so "pages in use" is directly a byte
    number).  ``pages_per_slot`` covers ``max_ctx`` tokens;
    ``total_pages`` is the shared pool capacity (scratch row NOT
    included)."""

    page_tokens: int
    pages_per_slot: int
    total_pages: int
    page_bytes: int       # actual bytes one page holds (≤ the budget)
    token_bytes: int      # K+V bytes per token across all layers
    total_bytes: int

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` cache positions."""
        return max(1, -(-int(tokens) // self.page_tokens))

    @property
    def slot_tokens(self) -> int:
        """Token capacity of one slot's full page table."""
        return self.pages_per_slot * self.page_tokens


@functools.lru_cache(maxsize=64)
def _plan_cached(n_layers: int, kv_width: int, itemsize: int,
                 slots: int, max_ctx: int, budget: int) -> KVPagePlan:
    token_bytes = 2 * n_layers * kv_width * itemsize  # K and V
    page_tokens = max(1, budget // token_bytes)
    pages_per_slot = max(1, -(-max_ctx // page_tokens))
    total_pages = slots * pages_per_slot
    return KVPagePlan(
        page_tokens=page_tokens,
        pages_per_slot=pages_per_slot,
        total_pages=total_pages,
        page_bytes=page_tokens * token_bytes,
        token_bytes=token_bytes,
        total_bytes=total_pages * page_tokens * token_bytes,
    )


def plan_kv_pages(n_layers: int, kv_width: int, dtype,
                  slots: int, max_ctx: int,
                  page_bytes: Optional[int] = None) -> KVPagePlan:
    """Plan the paged pool for a model fingerprint.

    ``kv_width`` is the per-token K (= V) feature width
    (``n_heads * head_dim``).  Cached per fingerprint — the same model
    served again reuses the plan object, and the gauges below always
    describe the ACTIVE plan."""
    plan = _plan_cached(int(n_layers), int(kv_width),
                        int(np.dtype(dtype).itemsize), int(slots),
                        int(max_ctx), resolve_page_bytes(page_bytes))
    record_plan(plan)
    return plan


def record_plan(plan: KVPagePlan) -> None:
    from horovod_tpu.serving import metrics as smetrics
    smetrics.set_kv_pool(in_use=0, total=plan.total_pages,
                         page_bytes=plan.page_bytes)


class PagePool:
    """Host-side page allocator over ``plan.total_pages`` page ids.

    Thread-safe; allocation is all-or-nothing (a request either gets
    every page its worst case needs at admission, or waits — the engine
    never hits a mid-decode out-of-pages).  Page ids are handed out
    lowest-first so freshly started pools allocate contiguously; the
    ``fragmentation`` stat reports how broken-up the free set has
    become (0 = one contiguous free run)."""

    def __init__(self, plan: KVPagePlan) -> None:
        self.plan = plan
        self._lock = threading.Lock()
        self._free = list(range(plan.total_pages - 1, -1, -1))  # pop() low-first
        self._high_water = 0

    # -- allocation ---------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` page ids, or None when the pool cannot cover it (the
        caller keeps the request WAITING — never a partial grant)."""
        if n <= 0:
            return []
        try:
            from horovod_tpu import chaos
            if any(kind == "starve"
                   for _, kind in chaos.fire("serving.kv")):
                return None  # injected starvation: refuse the grant
        except Exception:
            pass
        with self._lock:
            if n > len(self._free):
                return None
            pages = [self._free.pop() for _ in range(n)]
            self._high_water = max(self._high_water, self.in_use)
        self._publish()
        return pages

    def free(self, pages: List[int]) -> None:
        if not pages:
            return
        with self._lock:
            self._free.extend(pages)
            # keep low-first hand-out after churn
            self._free.sort(reverse=True)
        self._publish()

    # -- accounting ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.plan.total_pages

    @property
    def in_use(self) -> int:
        return self.plan.total_pages - len(self._free)

    @property
    def high_water(self) -> int:
        with self._lock:
            return max(self._high_water, self.in_use)

    def fragmentation(self) -> float:
        """1 − (largest contiguous free run / free pages): 0 when the
        free set is one run (or empty), → 1 as churn shreds it."""
        with self._lock:
            free = sorted(self._free)
        if not free:
            return 0.0
        longest = run = 1
        for a, b in zip(free, free[1:]):
            run = run + 1 if b == a + 1 else 1
            longest = max(longest, run)
        return 1.0 - longest / len(free)

    def stats(self) -> dict:
        return {"capacity": self.capacity, "in_use": self.in_use,
                "high_water": self.high_water,
                "fragmentation": round(self.fragmentation(), 4),
                "page_tokens": self.plan.page_tokens,
                "page_bytes": self.plan.page_bytes}

    def _publish(self) -> None:
        from horovod_tpu.serving import metrics as smetrics
        smetrics.set_kv_pool(in_use=self.in_use, total=self.capacity,
                             page_bytes=self.plan.page_bytes)
