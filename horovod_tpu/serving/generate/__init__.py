"""Token-level continuous batching: the generative decode engine.

One jit'd fixed-shape decode step over a static slot array, K/V
history in a byte-budgeted paged pool, prompts prefilled in chunks
that never stall live decode.  docs/SERVING.md "Continuous batching &
KV paging" is the narrative; the pieces:

* :mod:`.pages` — the paged KV-cache pool (plan + allocator);
* :mod:`.scheduler` — slot membership: FIFO page-gated admission,
  prefill chunking, step-boundary eviction;
* :mod:`.engine` — the engine itself plus the request-level gang
  baseline it is benched against.
"""

from horovod_tpu.serving.generate.engine import (GenerateEngine,
                                                 demo_gen_setup,
                                                 request_level_generate)
from horovod_tpu.serving.generate.pages import (KVPagePlan, PagePool,
                                                plan_kv_pages,
                                                resolve_page_bytes)
from horovod_tpu.serving.generate.scheduler import (GenRequest,
                                                    SlotScheduler)

__all__ = [
    "GenerateEngine", "demo_gen_setup", "request_level_generate",
    "KVPagePlan", "PagePool", "plan_kv_pages", "resolve_page_bytes",
    "GenRequest", "SlotScheduler",
]
