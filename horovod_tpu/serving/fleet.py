"""The elastic replica fleet: spawn, watch, heal, drain, scale out.

The serving analog of the elastic driver's world management
(docs/SERVING.md "Fleet"): a :class:`ReplicaFleet` owns N replica
PROCESSES (``python -m horovod_tpu.serving.replica``), monitors their
``/readyz`` probes, classifies every exit — **DRAINED** (exit code 0:
preemption/admin drain completed; a planned event, never failure
evidence) vs **FAILURE** (crash/SIGKILL) — and heals back to the
target size by respawning replacements on fresh ports.  The router's
endpoint view is the fleet's live READY set, so a draining or dead
replica drops out of rotation before requests discover it.

``scale_out`` is the autopilot seam: the ``serving-slo-scaleout``
policy (finding ``slo_breach`` → action ``scale_out``) runs the hook
the fleet registers, raising the target size — detection to
remediation with the same audit trail as every other autopilot action
(docs/OBSERVABILITY.md "Autopilot").
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common.config import env_float
from horovod_tpu.common.logging import get_logger
from horovod_tpu.serving import metrics as smetrics

Endpoint = Tuple[str, int]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _flight(kind: str, **fields) -> None:
    try:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(kind, **fields)
    except Exception:
        pass


class _Replica:
    def __init__(self, slot: int, incarnation: int, port: int,
                 proc: subprocess.Popen, log_path: str) -> None:
        self.slot = slot
        self.incarnation = incarnation
        self.port = port
        self.proc = proc
        self.log_path = log_path
        self.ready = False
        # last observed serving state (from the readyz probes / pin
        # responses): the per-version membership view reads these
        self.version: Optional[int] = None
        self.pinned: Optional[int] = None

    def log_tail(self, n: int = 2000) -> str:
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    @property
    def endpoint(self) -> Endpoint:
        return ("127.0.0.1", self.port)

    def name(self) -> str:
        return f"slot{self.slot}.{self.incarnation}"


class ReplicaFleet:
    """Local replica-process fleet.

    Args:
      size: initial target replica count.
      store_dir: durable sharded store every replica restores from and
        watches for hot swaps.
      dim: demo-model width forwarded to replicas.
      extra_env: env overrides for spawned replicas (chaos plans,
        serving knobs).
      poll_s: monitor loop interval.
    """

    MAX_EXITS = 100  # bounded exit-classification audit

    def __init__(self, size: int = 2, store_dir: Optional[str] = None,
                 dim: int = 16, extra_env: Optional[dict] = None,
                 poll_s: Optional[float] = None) -> None:
        self.target = size
        self.store_dir = store_dir
        self.dim = dim
        self.extra_env = dict(extra_env or {})
        self.poll_s = poll_s if poll_s is not None \
            else env_float("SERVING_FLEET_POLL_S", 0.25)
        self._replicas: Dict[int, _Replica] = {}
        self._incarnations = 0
        # per-slot version pins (docs/SERVING.md "Canary rollout"):
        # _pins is what the slot's replica serves NOW (re-applied on a
        # drained respawn); _heal_pins is what a replacement after a
        # FAILURE restores — the rollout controller sets it to the
        # incumbent for canary slots, so a crashed canary heals at the
        # incumbent version, not the candidate
        self._pins: Dict[int, int] = {}
        self._heal_pins: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.exits: List[dict] = []  # classification audit

    # -- lifecycle ----------------------------------------------------------
    def start(self, ready_timeout_s: float = 60.0) -> "ReplicaFleet":
        for slot in range(self.target):
            self._spawn(slot)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="hvd-serving-fleet",
                                         daemon=True)
        self._monitor.start()
        if not self.wait_ready(self.target, timeout_s=ready_timeout_s):
            raise RuntimeError(
                f"fleet: {self.target} replicas not ready within "
                f"{ready_timeout_s}s")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            replicas = list(self._replicas.values())
        for r in replicas:
            try:
                r.proc.terminate()
            except OSError:
                pass
        for r in replicas:
            try:
                r.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                r.proc.kill()
            try:
                os.unlink(r.log_path)
            except OSError:
                pass

    # -- spawning -----------------------------------------------------------
    def _spawn(self, slot: int) -> _Replica:
        with self._lock:
            self._incarnations += 1
            inc = self._incarnations
        port = _free_port()
        env = dict(os.environ)
        # rank-scoped chaos rules address replicas by SLOT (stable
        # across respawns — a replacement in the slot is the same
        # logical replica, and markers keep one-shot rules one-shot)
        env["HVD_TPU_RANK"] = str(slot)
        env.update(self.extra_env)
        cmd = [sys.executable, "-m", "horovod_tpu.serving.replica",
               "--port", str(port), "--dim", str(self.dim),
               "--replica-id", f"slot{slot}.{inc}"]
        if self.store_dir:
            cmd += ["--store-dir", self.store_dir]
        with self._lock:
            pin = self._pins.get(slot)
        if pin is not None:
            # a pinned slot's replacement joins AT the pin, never at
            # latest — a respawn during a rollout must not widen the
            # canary (docs/SERVING.md "Canary rollout")
            cmd += ["--pin-version", str(pin)]
        # log to a FILE, not a pipe: nobody drains a pipe while the
        # replica lives, and a full pipe would wedge it mid-request
        import tempfile
        log_fd, log_path = tempfile.mkstemp(
            prefix=f"hvd_serving_slot{slot}.{inc}_", suffix=".log")
        log_fh = os.fdopen(log_fd, "wb")
        proc = subprocess.Popen(cmd, env=env, stdout=log_fh,
                                stderr=subprocess.STDOUT)
        log_fh.close()  # the child holds its own handle now
        replica = _Replica(slot, inc, port, proc, log_path)
        with self._lock:
            self._replicas[slot] = replica
        _flight("serving_replica_spawn", slot=slot, incarnation=inc,
                port=port)
        return replica

    # -- monitoring ---------------------------------------------------------
    def _note_ready_doc(self, replica: _Replica, raw: bytes) -> None:
        try:
            doc = json.loads(raw)
        except Exception:
            return
        if isinstance(doc, dict):
            replica.version = doc.get("version")
            replica.pinned = doc.get("pinned")

    def _probe_ready(self, replica: _Replica) -> bool:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{replica.port}/readyz",
                    timeout=1.0) as r:
                self._note_ready_doc(replica, r.read())
                return r.status == 200
        except urllib.error.HTTPError as e:
            # a 503 (draining / still restoring) raises, but its body
            # still carries the readyz doc — per-version membership
            # keeps tracking a not-ready replica's observed version
            try:
                self._note_ready_doc(replica, e.read())
            except Exception:
                pass
            return False
        except Exception:
            return False

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                replicas = dict(self._replicas)
                target = self.target
            live = 0
            for slot, replica in replicas.items():
                rc = replica.proc.poll()
                if rc is None:
                    replica.ready = self._probe_ready(replica)
                    live += 1 if replica.ready else 0
                    continue
                # exited: classify.  Exit code 0 = the replica finished
                # its drain (preemption notice, admin drain) — DRAINED,
                # a planned event that is NEVER failure evidence
                # against the slot; anything else (SIGKILL shows as a
                # negative returncode) is a failure
                outcome = "drained" if rc == 0 else "failure"
                smetrics.inc_replica_exit(outcome)
                self.exits.append({
                    "slot": slot, "incarnation": replica.incarnation,
                    "rc": rc, "outcome": outcome,
                    "tail": replica.log_tail()})
                # the tail is captured; the dead incarnation's log file
                # must not accumulate under a respawn loop, nor may the
                # audit list grow without bound
                try:
                    os.unlink(replica.log_path)
                except OSError:
                    pass
                if len(self.exits) > self.MAX_EXITS:
                    del self.exits[: len(self.exits) - self.MAX_EXITS]
                _flight("serving_replica_exit", slot=slot,
                        incarnation=replica.incarnation, rc=rc,
                        outcome=outcome)
                get_logger().warning(
                    "serving fleet: replica %s exited rc=%s (%s); "
                    "respawning", replica.name(), rc, outcome)
                smetrics.inc_respawn()
                if outcome == "failure":
                    # heal-at-incumbent: a crash mid-rollout is not
                    # evidence the candidate deserves more traffic —
                    # the replacement joins at the heal pin (the
                    # rollout controller sets it to the incumbent for
                    # canary slots) rather than rejoining the canary
                    with self._lock:
                        heal = self._heal_pins.get(slot)
                        if heal is not None:
                            self._pins[slot] = heal
                self._spawn(slot)
            # scale-out: spawn slots beyond the current map.  NOT a
            # respawn — planned growth must not read as crash-healing
            # on the respawns counter (hvd_serving_scale_out_total
            # already audits it)
            with self._lock:
                missing = [s for s in range(target)
                           if s not in self._replicas]
            for slot in missing:
                self._spawn(slot)
            smetrics.set_fleet_gauges(live, target)

    # -- views --------------------------------------------------------------
    def endpoints(self) -> List[Endpoint]:
        """READY endpoints — wire this as the router's endpoint
        provider.  When NO replica reads ready (a probe-starved or
        mid-heal moment), degrade to every LIVE endpoint instead of an
        empty list: an accepted request retrying against a maybe-
        overloaded replica (503s are retried) beats failing outright —
        the zero-drop guarantee outranks probe freshness."""
        with self._lock:
            ready = [r.endpoint for r in self._replicas.values()
                     if r.ready and r.proc.poll() is None]
            if ready:
                return ready
            return [r.endpoint for r in self._replicas.values()
                    if r.proc.poll() is None]

    def all_endpoints(self) -> List[Endpoint]:
        with self._lock:
            return [r.endpoint for r in self._replicas.values()
                    if r.proc.poll() is None]

    def live_count(self) -> int:
        """STRICTLY ready replicas — health surfaces (the front's
        /readyz, heal checks) must not inherit endpoints()'s
        degrade-to-live fallback: an alive-but-draining fleet is not
        'ready'."""
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.ready and r.proc.poll() is None)

    def wait_ready(self, n: int, timeout_s: float = 60.0) -> bool:
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            with self._lock:
                replicas = list(self._replicas.values())
            ready = 0
            for r in replicas:
                if r.proc.poll() is None and self._probe_ready(r):
                    r.ready = True
                    ready += 1
            if ready >= n:
                return True
            time.sleep(0.2)
        return False

    def slots(self) -> List[int]:
        with self._lock:
            return sorted(s for s, r in self._replicas.items()
                          if r.proc.poll() is None)

    def pins(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._pins)

    def versions(self) -> Dict[int, Optional[int]]:
        """slot -> last observed serving weight version (refreshed by
        the monitor loop's readyz probes and by pin responses)."""
        with self._lock:
            return {s: r.version for s, r in self._replicas.items()
                    if r.proc.poll() is None}

    def members_by_version(self) -> Dict[Optional[int], List[Endpoint]]:
        """READY endpoints grouped by observed weight version — the
        router's version-split arms draw from this view."""
        out: Dict[Optional[int], List[Endpoint]] = {}
        with self._lock:
            for r in self._replicas.values():
                if r.ready and r.proc.poll() is None:
                    out.setdefault(r.version, []).append(r.endpoint)
        return out

    def endpoints_at(self, version: int) -> List[Endpoint]:
        """READY endpoints currently serving ``version``."""
        return self.members_by_version().get(int(version), [])

    # -- actions ------------------------------------------------------------
    def pin_slot(self, slot: int, version: Optional[int],
                 reason: str = "pin",
                 heal_version: Optional[int] = None) -> bool:
        """Pin one slot's replica to ``version`` via its ``/pin`` seam
        (``None`` unpins), and remember the pin so a respawn in the
        slot joins at the right version.  ``heal_version`` overrides
        what a replacement after a FAILURE restores: the rollout
        controller heals canary slots at the INCUMBENT — a crash
        mid-canary must shrink the canary, not re-grow it."""
        with self._lock:
            if version is None:
                self._pins.pop(slot, None)
                self._heal_pins.pop(slot, None)
            else:
                self._pins[slot] = int(version)
                self._heal_pins[slot] = int(
                    heal_version if heal_version is not None else version)
            replica = self._replicas.get(slot)
        _flight("serving_fleet_pin", slot=slot, version=version,
                reason=reason, heal_version=heal_version)
        if replica is None or replica.proc.poll() is not None:
            return False
        body = json.dumps({"version": version, "reason": reason}).encode()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{replica.port}/pin", data=body,
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10.0) as r:
                doc = json.loads(r.read())
            if isinstance(doc, dict):
                replica.version = doc.get("version")
                replica.pinned = doc.get("pinned")
            return True
        except Exception:
            get_logger().warning(
                "serving fleet: pin slot %d -> %s (%s) failed", slot,
                version, reason, exc_info=True)
            return False

    def unpin_slot(self, slot: int) -> bool:
        """Clear a slot's pin; its replica resumes chasing latest."""
        return self.pin_slot(slot, None, reason="unpin")

    def drain(self, slot: int) -> bool:
        """Ask one replica to drain (admin path; preemption notices
        reach replicas directly through the chaos/maintenance seam)."""
        with self._lock:
            replica = self._replicas.get(slot)
        if replica is None or replica.proc.poll() is not None:
            return False
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{replica.port}/drain", data=b"{}",
                method="POST")
            urllib.request.urlopen(req, timeout=2.0)
            return True
        except Exception:
            return False

    def scale_out(self, n: int = 1) -> int:
        """Raise the target size (the autopilot ``scale_out`` hook).
        Returns the new target; the monitor loop spawns the slots."""
        with self._lock:
            self.target += max(1, int(n))
            target = self.target
        _flight("serving_scale_out", target=target)
        get_logger().warning("serving fleet: scaling out to %d replicas",
                             target)
        smetrics._reg().counter(
            "hvd_serving_scale_out_total",
            help="fleet scale-outs (autopilot slo_breach remediation "
                 "or manual)").inc()
        return target

    def register_autopilot_hook(self) -> None:
        """Wire this fleet as the ``scale_out`` remediation target."""
        from horovod_tpu.autopilot import actions
        actions.register_scale_out_hook(lambda: self.scale_out(1))
