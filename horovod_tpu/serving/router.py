"""The serving front door: admission control, dispatch, hedging,
retry, and the request log the zero-drop guarantee is audited from.

Invariant (docs/SERVING.md): **an accepted request gets exactly one
successful response, or an explicit error — never a silent drop.**

* **Admission** is the only shed point the router owns: past
  ``max_inflight`` concurrently admitted requests, a submit is refused
  with :class:`~horovod_tpu.serving.batcher.SheddedError` (HTTP 429),
  counted (``hvd_serving_shed_total{where="admission"}``) and logged —
  backpressure is explicit.
* **Dispatch** posts the request to a ready replica.  A replica-side
  backpressure answer (429/503) or death (connection reset/refused,
  5xx, timeout) triggers **retry** against the next replica; a replica
  that is merely SLOW past ``hedge_ms`` triggers a **hedge** — the
  request is duplicated to a second replica and the first success
  wins.  Replica-side idempotency (the response cache keyed by request
  id) makes this fan-out safe: a duplicate never recomputes a request
  that already answered.
* The **request log** (JSONL, optional) records one ``accepted`` line
  per admission and exactly one terminal line (``ok`` / ``failed`` /
  with sheds logged at admission) — the chaos acceptance scenarios
  replay it to prove zero drops under replica SIGKILL and drain.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
import urllib.error
import urllib.request
import zlib
from typing import Callable, List, Optional, Sequence, Tuple

from horovod_tpu import tracing
from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.common.logging import get_logger
from horovod_tpu.serving import ledger
from horovod_tpu.serving import metrics as smetrics
from horovod_tpu.serving.batcher import SheddedError
from horovod_tpu.serving.metrics import LatencyWindow

Endpoint = Tuple[str, int]

DEFAULT_REQLOG_MAX_BYTES = 16 * 1024 * 1024


class RequestFailed(RuntimeError):
    """An accepted request exhausted every retry/hedge (explicit
    terminal error — logged, counted, surfaced; not a drop)."""


class RequestRejected(RuntimeError):
    """A replica answered a DEFINITIVE client error (4xx other than
    backpressure): retrying it anywhere would get the same answer —
    terminal immediately, logged as ``rejected``, never a retry storm
    and never a zero-drop violation."""

    def __init__(self, code: int, doc: dict) -> None:
        super().__init__(f"HTTP {code}: {doc.get('error', doc)}")
        self.code = code
        self.doc = doc


class RequestLog:
    """JSONL accounting with size-based rotation, thread-safe; ``None``
    path = in-memory only (the entries list is still kept, bounded).

    The on-disk file rotates at ``HVD_TPU_SERVING_REQLOG_MAX_BYTES``
    (one previous generation kept as ``<path>.1`` — the OBS-store
    treatment, :class:`horovod_tpu.metrics.timeseries.SeriesWriter`),
    always at a line boundary, so each generation's lines are a
    self-consistent audit window and :func:`read_request_log` reads
    across the boundary in recording order.  The exactly-once
    ``accounting()`` audit runs over the in-memory entries and is
    untouched by rotation."""

    MAX_MEMORY = 100_000

    def __init__(self, path: Optional[str] = None,
                 max_bytes: Optional[int] = None) -> None:
        self._path = path
        self._lock = threading.Lock()
        self.entries: List[dict] = []
        self.trimmed = 0  # lines dropped from memory by the MAX_MEMORY cap
        self.max_bytes = int(max_bytes) if max_bytes else env_int(
            "SERVING_REQLOG_MAX_BYTES", DEFAULT_REQLOG_MAX_BYTES)
        self._fh = None
        self._written = 0
        self._closed = False
        if path:
            # a bad path fails LOUDLY at construction (an audit log
            # that silently never existed is worse than a crash);
            # mid-life errors degrade to dropped lines below
            self._open()

    def _open(self):
        self._fh = open(self._path, "a", buffering=1)
        self._written = self._fh.tell()
        return self._fh

    def note(self, req_id: str, outcome: str, **fields) -> None:
        doc = {"ts": round(time.time(), 4), "id": req_id,
               "outcome": outcome, **fields}
        with self._lock:
            self.entries.append(doc)
            if len(self.entries) > self.MAX_MEMORY:
                cut = self.MAX_MEMORY // 10
                del self.entries[:cut]
                self.trimmed += cut
            if self._path is not None and not self._closed:
                try:
                    line = json.dumps(doc) + "\n"
                    # lazy reopen heals a transient mid-life failure
                    # (the OBS SeriesWriter's contract); close() is
                    # final — the flag above stops late completions
                    # from resurrecting the handle
                    fh = self._fh or self._open()
                    if self._written > 0 and \
                            self._written + len(line) > self.max_bytes:
                        fh.close()
                        self._fh = None
                        os.replace(self._path, self._path + ".1")
                        fh = self._open()
                    fh.write(line)
                    self._written += len(line)
                except OSError:
                    pass  # accounting stays in memory; never raise

    def seq_now(self) -> int:
        """Monotonic count of lines ever noted — unlike a raw index
        into ``entries``, it survives the in-memory trim, so windowed
        readers (the rollout stage window) can anchor on it without
        over-skipping entries after a trim."""
        with self._lock:
            return self.trimmed + len(self.entries)

    def since(self, seq: int) -> List[dict]:
        """Entries noted at-or-after absolute sequence ``seq`` (a prior
        :meth:`seq_now`), trim-compensated; entries the cap already
        dropped are gone, but nothing that survived is skipped."""
        with self._lock:
            return list(self.entries[max(0, seq - self.trimmed):])

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def accounting(self) -> dict:
        """{outcome: count} plus the exactly-once audit, keyed by the
        per-SUBMISSION sequence number (``seq``): a client may reuse a
        request id — that is what idempotency is FOR — but every
        accepted submission must terminate exactly once.
        ``unanswered`` = accepted with NO terminal entry at all (a
        true accounting hole); explicit ``failed``/``rejected``
        terminals are counted in ``outcomes``, not hidden there."""
        with self._lock:
            entries = list(self.entries)
        by_outcome: dict = {}
        by_version: dict = {}
        accepted: dict = {}
        ok: dict = {}
        terminal: set = set()
        for e in entries:
            by_outcome[e["outcome"]] = by_outcome.get(e["outcome"], 0) + 1
            seq = e.get("seq")
            if seq is None:
                continue
            if e["outcome"] == "accepted":
                accepted[seq] = e["id"]
            elif e["outcome"] == "ok":
                ok[seq] = ok.get(seq, 0) + 1
                terminal.add(seq)
                # per-version success counts: rollout verdicts are
                # auditable from the log alone (docs/SERVING.md
                # "Canary rollout")
                v = e.get("version")
                v = "unversioned" if v is None else v
                by_version[v] = by_version.get(v, 0) + 1
            elif e["outcome"] in ("failed", "rejected"):
                terminal.add(seq)
        return {
            "outcomes": by_outcome,
            "accepted": len(accepted),
            "answered_ok": len(ok),
            "unanswered": sorted(accepted[s] for s in
                                 set(accepted) - terminal),
            "answered_twice": sorted(accepted.get(s, "?") for s, n in
                                     ok.items() if n > 1),
            "by_version": by_version,
        }


def read_request_log(path: str) -> List[dict]:
    """Read a request log back, rotated generation first so lines come
    out in recording order; torn trailing lines (a crash mid-append)
    are skipped.  THE one rotated-JSONL reader — shared with the
    causal-tracing planes so both sides always agree on the format."""
    from horovod_tpu.tracing.reader import read_jsonl
    return read_jsonl(path)


class Router:
    """Dispatches requests across a replica fleet.

    Args:
      endpoints: static list of ``(host, port)`` replica endpoints, or
        a zero-arg callable returning the CURRENT list (the fleet wires
        its live view in, so respawns/scale-outs are picked up per
        request).
      max_inflight: admission budget (429 beyond it).
      hedge_ms: duplicate a silent in-flight request to a second
        replica after this long (0 disables hedging).
      attempt_timeout_s: per-dispatch HTTP timeout.
      max_attempts: total dispatch attempts per request (retries +
        hedges; the deadline caps it too).
      log_path: JSONL request-log path (None = in-memory only).
    """

    def __init__(self, endpoints, max_inflight: Optional[int] = None,
                 hedge_ms: Optional[float] = None,
                 attempt_timeout_s: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 log_path: Optional[str] = None) -> None:
        self._endpoints = endpoints if callable(endpoints) \
            else (lambda: list(endpoints))
        self.max_inflight = max_inflight if max_inflight \
            else env_int("SERVING_MAX_INFLIGHT", 256)
        self.hedge_s = (hedge_ms if hedge_ms is not None
                        else env_float("SERVING_HEDGE_MS", 150.0)) / 1000.0
        self.attempt_timeout_s = attempt_timeout_s \
            if attempt_timeout_s is not None \
            else env_float("SERVING_ATTEMPT_TIMEOUT_S", 5.0)
        self.max_attempts = max_attempts if max_attempts \
            else env_int("SERVING_MAX_ATTEMPTS", 6)
        self.default_deadline_s = default_deadline_s \
            if default_deadline_s is not None \
            else env_float("SERVING_DEADLINE_MS", 30_000.0) / 1000.0
        self.log = RequestLog(log_path)
        self.window = LatencyWindow()
        self._inflight = threading.BoundedSemaphore(self.max_inflight)
        self._inflight_n = 0
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._rr = itertools.count()  # per-request round-robin offset
        # version split (docs/SERVING.md "Canary rollout"): when a
        # rollout is live, requests are deterministically assigned to
        # the canary or incumbent arm by request id, and retries/hedges
        # rotate WITHIN the arm — a canary request never silently
        # escapes to the incumbent (and vice versa) unless its arm is
        # empty, in which case zero-drop outranks split fidelity
        self._split: Optional[dict] = None
        # (host, port) -> last weight version OBSERVED answering there
        # (fed by every 200 dispatch) — hedge/retry log lines attribute
        # outcomes per version from this map
        self._ep_versions: dict = {}
        # windows must close on IDLE too: with rolls driven only by
        # observe(), a fleet whose traffic stopped would freeze the
        # qps/p50/p99 gauges at their last busy values forever
        self._roller_stop = threading.Event()
        threading.Thread(target=self._roll_loop, daemon=True,
                         name="hvd-serving-window-roll").start()

    def _roll_loop(self) -> None:
        while not self._roller_stop.wait(self.window.window_s):
            try:
                self.window.maybe_roll()
            except Exception:
                pass

    # -- version split (canary rollout) -------------------------------------
    def set_version_split(self, pct: int, canary_eps,
                          incumbent_eps,
                          canary_version: Optional[int] = None,
                          incumbent_version: Optional[int] = None
                          ) -> None:
        """Install a version split: ``pct``% of requests go to the
        canary arm, the rest to the incumbent arm.  Each arm is a list
        of endpoints or a zero-arg callable returning the CURRENT list
        (the fleet's ``endpoints_at(version)`` view, so heals and
        repins are picked up per request).  Assignment is by request
        id (crc32 bucket), so an idempotent replay of a request lands
        on the SAME arm — and is answered by the same version — as the
        original."""
        pct = max(0, min(100, int(pct)))
        self._split = {
            "pct": pct,
            "canary": canary_eps if callable(canary_eps)
            else (lambda eps=list(canary_eps): list(eps)),
            "incumbent": incumbent_eps if callable(incumbent_eps)
            else (lambda eps=list(incumbent_eps): list(eps)),
            "canary_version": canary_version,
            "incumbent_version": incumbent_version,
        }
        smetrics.set_rollout_canary_pct(pct)

    def clear_version_split(self) -> None:
        self._split = None
        smetrics.set_rollout_canary_pct(0)

    def version_split(self) -> Optional[dict]:
        s = self._split
        if s is None:
            return None
        return {"pct": s["pct"],
                "canary_version": s["canary_version"],
                "incumbent_version": s["incumbent_version"]}

    def _pick_arm(self, req_id: str) -> Tuple[List[Endpoint],
                                              Optional[str]]:
        """The request's endpoint pool.  No split: the full fleet.
        Split: the arm its id hashes into — empty arms degrade to the
        full fleet (counted) rather than failing the request."""
        split = self._split
        if split is None:
            return list(self._endpoints()), None
        bucket = zlib.crc32(req_id.encode()) % 100
        arm = "canary" if bucket < split["pct"] else "incumbent"
        try:
            eps = list(split[arm]())
        except Exception:
            eps = []
        if not eps:
            smetrics._reg().counter(
                "hvd_serving_rollout_split_fallback_total",
                help="requests whose version-split arm was empty and "
                     "fell back to the full fleet (zero-drop outranks "
                     "split fidelity)",
                labels={"arm": arm}).inc()
            return list(self._endpoints()), f"{arm}-fallback"
        return eps, arm

    def _version_at(self, ep: Endpoint) -> Optional[int]:
        """Best-effort weight-version attribution for an endpoint.
        Under a split, CURRENT arm membership names the version — a
        poisoned candidate that fails every request has never answered
        200, so the observed-version map alone would attribute its
        failures to the version it previously served (or to nothing)
        and the canary error window would never accrue.  Outside a
        split, the last version observed answering there."""
        split = self._split
        if split is not None:
            for arm_name in ("canary", "incumbent"):
                try:
                    if ep in split[arm_name]():
                        return split[f"{arm_name}_version"]
                except Exception:
                    pass
        return self._ep_versions.get(ep)

    # -- dispatch plumbing --------------------------------------------------
    def _post(self, ep: Endpoint, body: bytes, timeout: float,
              ctx=None, path: str = "/infer") -> Tuple[int, dict]:
        url = f"http://{ep[0]}:{ep[1]}{path}"
        headers = {"Content-Type": "application/json"}
        if ctx is not None:
            # the attempt's OWN span travels as the traceparent header;
            # the replica's spans become its children
            headers[tracing.TRACEPARENT] = ctx.traceparent
        req = urllib.request.Request(
            url, data=body, method="POST", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read())
            except Exception:
                doc = {"error": str(e)}
            return e.code, doc

    def _fire(self, ep: Endpoint, body: bytes, deadline: float,
              results: "queue.Queue", ctx=None,
              path: str = "/infer") -> None:
        def run():
            timeout = min(self.attempt_timeout_s,
                          max(deadline - time.monotonic(), 0.05))
            t0 = time.monotonic()
            wall0 = time.time()
            try:
                code, doc = self._post(ep, body, timeout, ctx=ctx,
                                       path=path)
                if code == 200 and isinstance(doc, dict) \
                        and doc.get("version") is not None:
                    self._ep_versions[ep] = int(doc["version"])
                results.put((ep, code, doc, None, t0,
                             time.monotonic()))
                err = None
            except Exception as e:
                results.put((ep, None, None, e, t0, time.monotonic()))
                code, err = None, e
            # every attempt records its span — including the hedge
            # loser whose answer arrives after the request returned:
            # the causal tree must cover BOTH replicas a hedge touched
            tracing.record_span(
                "serving", "dispatch", ctx, start=wall0,
                dur_s=time.monotonic() - t0,
                target=f"{ep[0]}:{ep[1]}", code=code,
                error=repr(err) if err is not None else None)

        threading.Thread(target=run, daemon=True,
                         name="hvd-serving-dispatch").start()

    # -- the public request path --------------------------------------------
    def submit(self, x, req_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace=None) -> dict:
        """Blocking request.  Returns the replica's response doc.
        Raises :class:`SheddedError` at admission (429 — explicit
        backpressure) or :class:`RequestFailed` when an ACCEPTED
        request exhausts retries/hedges inside its deadline (explicit
        terminal error, logged).  ``trace`` is the CALLER's trace
        context (a front end decodes the client's ``traceparent``
        header into it); the request's root span is its child, or a
        fresh trace when the client sent none."""
        payload = {"x": x if isinstance(x, list)
                   else list(map(float, x))}
        return self._submit(payload, "/infer", True, x_req_id=req_id,
                            deadline_s=deadline_s, trace=trace)

    def submit_generate(self, prompt, max_new: int = 16,
                        req_id: Optional[str] = None,
                        deadline_s: Optional[float] = None,
                        trace=None) -> dict:
        """Blocking GENERATE request: same admission/accounting
        contract as :meth:`submit`, dispatched to ``/generate`` with
        hedging DISABLED — a hedge would land the same idempotency key
        on a SECOND replica whose in-flight table has never seen it,
        and two replicas would decode the same stream.  Within one
        replica, duplicates (retries after a timeout) still dedupe on
        the key before decode starts; the terminal ``ok`` log line
        records ``tokens_emitted`` so the exactly-once audit covers the
        multi-token response."""
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new": int(max_new)}
        return self._submit(payload, "/generate", False,
                            x_req_id=req_id, deadline_s=deadline_s,
                            trace=trace)

    def _submit(self, payload: dict, path: str, allow_hedge: bool,
                x_req_id: Optional[str], deadline_s: Optional[float],
                trace) -> dict:
        seq = next(self._seq)
        req_id = x_req_id
        if req_id is None:
            req_id = f"req-{seq}-{time.monotonic_ns()}"
        root = tracing.child(trace, "serving") if trace is not None \
            else tracing.new_trace("serving")
        if not self._inflight.acquire(blocking=False):
            smetrics.inc_shed("admission")
            self.window.note_shed()
            self.log.note(req_id, "shed", seq=seq, where="admission",
                          **tracing.fields(root))
            raise SheddedError("router inflight budget exhausted")
        with self._lock:
            self._inflight_n += 1
            smetrics.set_inflight(self._inflight_n)
        smetrics.inc_accepted()
        self.log.note(req_id, "accepted", seq=seq,
                      **tracing.fields(root))
        t0 = time.monotonic()
        wall0 = time.time()
        try:
            dmeta: dict = {}
            doc = self._dispatch(req_id, payload, deadline_s, root,
                                 path=path, allow_hedge=allow_hedge,
                                 meta=dmeta)
            latency = time.monotonic() - t0
            stages = self._close_books(t0, latency, dmeta, doc)
            doc["stages"] = {k: round(v, 6)
                             for k, v in stages.items()}
            tracing.record_span("serving", "request", root, start=wall0,
                                dur_s=latency,
                                replica=doc.get("replica"),
                                version=doc.get("version"),
                                **{f"stage_{k}": round(v, 6)
                                   for k, v in stages.items()
                                   if v > 0})
            smetrics.inc_completed()
            if doc.get("version") is not None:
                # the router-side registry mirrors the version it just
                # OBSERVED serving — so a front-process /metrics scrape
                # (metrics top "weights vN") reports live truth without
                # reaching into replica registries
                smetrics.set_weight_version(int(doc["version"]))
            ttft = doc.get("ttft_s")
            self.window.observe(
                latency, stages=stages,
                trace=getattr(root, "trace_id", None),
                req_id=req_id, version=doc.get("version"),
                ttft_s=float(ttft) if ttft is not None else None)
            extra = {}
            if doc.get("tokens_emitted") is not None:
                # multi-token responses: the audit line carries how
                # many tokens this exactly-one success delivered
                extra["tokens_emitted"] = int(doc["tokens_emitted"])
            self.log.note(req_id, "ok", seq=seq,
                          latency_s=round(latency, 6),
                          replica=doc.get("replica"),
                          version=doc.get("version"),
                          stages=doc["stages"], **extra,
                          **tracing.fields(root))
            return doc
        except RequestRejected as e:
            # the replica ANSWERED — with a client error.  Not a drop,
            # not a fleet failure: its own outcome + counter
            smetrics._reg().counter(
                "hvd_serving_rejected_total",
                help="accepted requests answered a definitive client "
                     "error (4xx) by a replica — terminal, never "
                     "retried").inc()
            self.log.note(req_id, "rejected", seq=seq, code=e.code,
                          error=str(e), **tracing.fields(root))
            raise
        except Exception as e:
            smetrics.inc_failed()
            self.log.note(req_id, "failed", seq=seq, error=repr(e),
                          **tracing.fields(root))
            raise
        finally:
            self._inflight.release()
            with self._lock:
                self._inflight_n -= 1
                smetrics.set_inflight(self._inflight_n)

    def _close_books(self, t0: float, latency: float, dmeta: dict,
                     doc: dict) -> dict:
        """Decompose an accepted request's wall clock into ledger
        stages (docs/OBSERVABILITY.md "Serving request ledger"):
        router-side ``admission``/``hedge_wait``/``dispatch`` from the
        attempt timing ``_dispatch`` reported, merged with the
        replica/engine stages the response doc carried.  Whatever
        neither side measured stays ``unattributed`` — the books close
        on the request's true end-to-end latency, never on a guess."""
        stages = {k: max(float(v), 0.0)
                  for k, v in (doc.get("stages") or {}).items()
                  if isinstance(v, (int, float))}
        replica_s = sum(stages.values())
        start = dmeta.get("start", t0)
        win_launch = dmeta.get("win_launch")
        if win_launch is not None:
            first = dmeta.get("first_launch", start)
            recv = dmeta.get("win_recv", win_launch)
            stages["admission"] = max(start - t0, 0.0)
            hedge = max(win_launch - first, 0.0)
            if hedge > 0:
                # the winner was a hedge/retry: its launch offset from
                # the FIRST attempt is time spent waiting out a slow or
                # dead primary
                stages["hedge_wait"] = hedge
            # dispatch = pre-launch prep (arm pick, body build) + the
            # winning attempt's network/serialization overhead around
            # the time the replica accounted for itself
            stages["dispatch"] = max(first - start, 0.0) + max(
                recv - win_launch - replica_s, 0.0)
        return ledger.close_books(latency, stages)

    def _dispatch(self, req_id: str, payload: dict, deadline_s,
                  root=None, path: str = "/infer",
                  allow_hedge: bool = True,
                  meta: Optional[dict] = None) -> dict:
        # ``meta`` (out-param): attempt timing for the request ledger —
        # dispatch entry, first-attempt launch, and the WINNING
        # attempt's launch/receive marks (hedge_wait = winner launch −
        # first launch; dispatch = prep + network around the winner's
        # replica time)
        t_dispatch = time.monotonic()
        if meta is not None:
            meta["start"] = t_dispatch
        deadline = time.monotonic() + (
            deadline_s if deadline_s is not None
            else self.default_deadline_s)
        body = json.dumps({
            "id": req_id, **payload,
            "deadline_ms": max((deadline - time.monotonic()) * 1000.0,
                               1.0),
        }).encode()
        eps, arm = self._pick_arm(req_id)
        if not eps:
            raise RequestFailed("no replica endpoints")
        # spread primaries round-robin across the pool (the whole
        # fleet, or the request's version-split arm); retries/hedges
        # continue the rotation so they land on a DIFFERENT replica —
        # and, under a split, stay WITHIN the arm
        start = next(self._rr) % len(eps)
        rotation = itertools.cycle(
            list(range(start, len(eps))) + list(range(start)))
        arm_size = len(eps)
        widened = False

        def widen():
            # a DEAD arm must not fail the request: the empty-arm rule
            # (zero-drop outranks split fidelity) applied mid-flight —
            # once every arm replica has refused/died, the retry pool
            # becomes the REST of the fleet, counted as a fallback
            nonlocal eps, rotation, widened
            widened = True
            rest = [e for e in self._endpoints() if e not in eps]
            if not rest:
                return
            eps = rest
            rotation = itertools.cycle(range(len(eps)))
            smetrics._reg().counter(
                "hvd_serving_rollout_split_fallback_total",
                help="requests whose version-split arm was empty and "
                     "fell back to the full fleet (zero-drop outranks "
                     "split fidelity)",
                labels={"arm": arm}).inc()
        results: "queue.Queue" = queue.Queue()
        attempts = 0
        outstanding = 0
        tried = []
        spans = []  # one per attempt, aligned with `tried`
        launched = []  # launch monotonic marks, aligned with `tried`

        def launch():
            nonlocal attempts, outstanding
            if attempts >= self.max_attempts:
                return False
            ep = eps[next(rotation)]
            attempts += 1
            outstanding += 1
            tried.append(ep)
            # every attempt — primary, hedge, retry — is a child of the
            # request's root span: the duplicates share the trace id
            # and are SIBLINGS of each other, so the causal tree shows
            # one request fanning out across replicas
            ctx = tracing.child(root, "serving")
            spans.append(ctx)
            launched.append(time.monotonic())
            self._fire(ep, body, deadline, results, ctx=ctx, path=path)
            return True

        launch()
        hedged = False
        last_error: Optional[str] = None
        while time.monotonic() < deadline:
            # wait for an answer; hedge once if the fleet has a spare
            # replica and the primary has gone silent past hedge_s —
            # never for /generate (allow_hedge=False): a hedged decode
            # stream on a second replica cannot dedupe on the key
            can_hedge = (allow_hedge and self.hedge_s > 0 and not hedged
                         and len(eps) > 1
                         and attempts < self.max_attempts)
            timeout = min(self.hedge_s if can_hedge else 0.25,
                          max(deadline - time.monotonic(), 0.01))
            try:
                ep, code, doc, err, a_t0, a_recv = \
                    results.get(timeout=timeout)
            except queue.Empty:
                if can_hedge:
                    hedged = True
                    if launch():  # appends the hedge TARGET to tried
                        smetrics.inc_hedged()
                        self.log.note(
                            req_id, "hedged", to=str(tried[-1]),
                            version=self._version_at(tried[-1]),
                            arm=arm, **tracing.fields(spans[-1]))
                elif outstanding == 0:
                    # everything launched has answered badly and the
                    # attempt budget may still allow a retry
                    if not launch():
                        break
                continue
            outstanding -= 1
            if code == 200 and isinstance(doc, dict):
                if meta is not None:
                    meta.update(
                        first_launch=launched[0] if launched
                        else t_dispatch,
                        win_launch=a_t0, win_recv=a_recv,
                        attempts=attempts, hedged=hedged)
                return doc
            if code is not None and 400 <= code < 500 \
                    and code not in (408, 429):
                # a definitive client error (bad payload, bad width):
                # every replica would answer the same — terminal, not
                # a reason to burn the attempt budget fleet-wide
                raise RequestRejected(code, doc if isinstance(doc, dict)
                                      else {"error": str(doc)})
            last_error = (f"{ep[0]}:{ep[1]} -> "
                          + (repr(err) if err is not None
                             else f"HTTP {code}: {doc}"))
            # 429/503 = replica backpressure/drain; 5xx/conn-error =
            # replica sick or dead: in every case the survivor is the
            # answer — retry there (counted only when a retry actually
            # LAUNCHES: an exhausted attempt budget is not a retry)
            if arm is not None and not widened \
                    and len(set(tried)) >= arm_size:
                widen()
            if launch():
                smetrics.inc_retried()
                self.log.note(
                    req_id, "retried", after=last_error,
                    after_version=self._version_at(ep),
                    to=str(tried[-1]),
                    version=self._version_at(tried[-1]),
                    arm=arm, **tracing.fields(spans[-1]))
            elif outstanding == 0:
                break
            # tiny backoff so a fully-shedding fleet is not hammered
            time.sleep(0.01)
        raise RequestFailed(
            f"request {req_id}: no successful response within "
            f"deadline/attempts ({attempts} attempts; last: "
            f"{last_error})")

    # -- introspection ------------------------------------------------------
    def accounting(self) -> dict:
        return self.log.accounting()

    def close(self) -> None:
        self._roller_stop.set()
        self.window.maybe_roll(force=True)
        self.log.close()


def ready_endpoints(candidates: Sequence[Endpoint],
                    timeout: float = 1.0) -> List[Endpoint]:
    """Filter ``candidates`` by their ``/readyz`` probe — the fleet's
    router view (a draining or still-restoring replica answers 503 and
    drops out of rotation here, BEFORE requests discover it)."""
    out = []
    for host, port in candidates:
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/readyz", timeout=timeout) as r:
                if r.status == 200:
                    out.append((host, port))
        except Exception:
            pass
    return out
