"""Zero-drop online serving: the trained model meets live traffic.

The serving plane composes what the training stack already proved
(docs/SERVING.md): an elastic replica fleet (process-managed, healed to
target size, DRAINED-vs-FAILURE exit classification), a continuous
dynamic batcher feeding a compiled forward step (bounded queue,
max-batch/max-wait knobs, per-request deadlines), a hedging/retrying
router with idempotent request ids (a replica killed mid-batch costs
latency, never a dropped request), zero-downtime hot weight swap from
the durable sharded checkpoint store, explicit 429 load-shedding under
backpressure, drain semantics reusing the preemption-notice plumbing,
and per-request p50/p99 SLO gauges with an autopilot ``slo_breach`` →
scale-out policy.

Generative traffic decodes at TOKEN granularity through the
continuous-batching engine (:mod:`horovod_tpu.serving.generate`): one
jit'd fixed-shape decode step over a static slot array, paged KV-cache
pool, prefill/decode split — replicas gain a ``generate`` mode and the
router a hedging-free :meth:`Router.submit_generate` path
(docs/SERVING.md "Continuous batching & KV paging").

Reference analog: the reference's elastic driver plus its Spark/Ray
integrations ship the serve-from-the-training-fleet story
(PAPER.md L6/L7); here it ships as a robustness guarantee — under
replica kills, preemption notices, and partitions, **no accepted
request is ever dropped** (chaos-proven: tests/test_serving.py).
"""

from horovod_tpu.serving.batcher import (DeadlineError, DrainingError,
                                         DynamicBatcher, PendingRequest,
                                         SheddedError)
from horovod_tpu.serving.fleet import ReplicaFleet
from horovod_tpu.serving.ledger import (STAGES, BurnRateSlo,
                                        ExemplarRing, WindowBooks,
                                        close_books, dominant_stage,
                                        quantile, residual_fraction)
from horovod_tpu.serving.metrics import LatencyWindow
from horovod_tpu.serving.replica import (ReplicaServer, demo_apply,
                                         demo_params)
from horovod_tpu.serving.generate import (GenerateEngine, GenRequest,
                                          KVPagePlan, PagePool,
                                          SlotScheduler, demo_gen_setup,
                                          plan_kv_pages,
                                          request_level_generate)
from horovod_tpu.serving.router import (RequestFailed, RequestLog,
                                        RequestRejected, Router,
                                        ready_endpoints)
from horovod_tpu.serving.rollout import (RolloutConfig,
                                         RolloutController)

__all__ = [
    "DynamicBatcher", "PendingRequest", "SheddedError", "DrainingError",
    "DeadlineError", "ReplicaServer", "demo_apply", "demo_params",
    "Router", "RequestLog", "RequestFailed", "RequestRejected",
    "ready_endpoints", "ReplicaFleet", "LatencyWindow",
    "GenerateEngine", "GenRequest", "KVPagePlan", "PagePool",
    "SlotScheduler", "demo_gen_setup", "plan_kv_pages",
    "request_level_generate", "RolloutConfig", "RolloutController",
    "STAGES", "BurnRateSlo", "ExemplarRing", "WindowBooks",
    "close_books", "dominant_stage", "quantile", "residual_fraction",
]
