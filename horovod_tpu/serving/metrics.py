"""Serving-plane metrics: per-request latency SLOs on the fleet plane.

Every number the zero-drop guarantee is proven from lives here
(docs/SERVING.md): request admission/completion/shed counters (a shed
is EXPLICIT — counted and answered 429, never a silent drop), hedge and
retry counters, queue/inflight gauges, the latency histogram, and a
windowed percentile tracker that publishes ``hvd_serving_p50/p99``
gauges, records one ``{"serving": ...}`` point per window into the
step time-series store (rendered by ``python -m horovod_tpu.metrics
history --serving``), and reports an ``slo_breach`` anomaly finding
when the windowed p99 stays over ``HVD_TPU_SERVING_SLO_P99_MS`` —
which the autopilot's ``serving-slo-scaleout`` policy turns into a
fleet scale-out (docs/OBSERVABILITY.md "Autopilot").
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.metrics.registry import default_registry
from horovod_tpu.serving import ledger

#: latency buckets: serving answers in milliseconds, not the step-time
#: seconds the default buckets are shaped for
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _reg():
    return default_registry()


def inc_accepted() -> None:
    _reg().counter("hvd_serving_accepted_total",
                   help="requests admitted past the router's "
                        "admission control").inc()


def inc_completed() -> None:
    _reg().counter("hvd_serving_completed_total",
                   help="accepted requests answered with exactly one "
                        "successful response").inc()


def inc_failed() -> None:
    _reg().counter("hvd_serving_failed_total",
                   help="accepted requests that exhausted every "
                        "retry/hedge before their deadline").inc()


def inc_shed(where: str) -> None:
    """An EXPLICIT load-shed (429): ``where`` names the backpressure
    point — ``admission`` (router inflight budget), ``queue`` (replica
    batch queue full), ``deadline`` (expired before compute),
    ``draining`` (replica refusing new work), ``chaos`` (injected)."""
    _reg().counter("hvd_serving_shed_total",
                   help="requests explicitly load-shed (429), per "
                        "backpressure point",
                   labels={"where": where}).inc()


def inc_hedged() -> None:
    _reg().counter("hvd_serving_hedged_total",
                   help="hedge requests launched at a second replica "
                        "after the hedge timeout").inc()


def inc_retried() -> None:
    _reg().counter("hvd_serving_retried_total",
                   help="requests re-dispatched to a surviving replica "
                        "after a replica error/death").inc()


def inc_swap() -> None:
    _reg().counter("hvd_serving_swaps_total",
                   help="zero-downtime hot weight swaps applied from "
                        "the durable sharded store").inc()


def set_weight_version(step: int) -> None:
    _reg().gauge("hvd_serving_weight_version",
                 help="durable-store step of the weights currently "
                      "serving").set(float(step))


def inc_weight_swap(reason: str) -> None:
    """Every ``(version, params)`` flip lands here once, per cause —
    ``chase`` (the swapper following the store's latest commit),
    ``pin`` (a rollout controller pinning a candidate/incumbent) or
    ``rollback`` (repin to the incumbent during an auto-rollback).  The
    weight version gauge alone cannot show a BACKWARD move after the
    fact; this counter plus the ``weight_swap`` flight event are what
    the autopsy reads the rollback from."""
    _reg().counter("hvd_serving_weight_swaps_total",
                   help="weight-version flips, per cause (chase=follow "
                        "latest commit, pin=rollout pin, "
                        "rollback=repin to incumbent)",
                   labels={"reason": reason}).inc()


# ---------------------------------------------------------------------------
# Canary weight rollout (horovod_tpu/serving/rollout/)
# ---------------------------------------------------------------------------
#: rollout state machine positions, as published on the state gauge
ROLLOUT_STATES = ("idle", "canary", "expanding", "promoted",
                  "rolling_back", "rolled_back")


def set_rollout_state(state: str) -> None:
    _reg().gauge("hvd_serving_rollout_state",
                 help="rollout state machine position (0=idle, "
                      "1=canary, 2=expanding, 3=promoted, "
                      "4=rolling_back, 5=rolled_back)").set(
        float(ROLLOUT_STATES.index(state))
        if state in ROLLOUT_STATES else -1.0)


def set_rollout_canary_pct(pct: float) -> None:
    _reg().gauge("hvd_serving_rollout_canary_pct",
                 help="traffic percentage currently routed to the "
                      "candidate weight version (0 = no active "
                      "split)").set(float(pct))


def inc_rollout_verdict(verdict: str) -> None:
    _reg().counter("hvd_serving_rollout_verdicts_total",
                   help="per-version SLO/quality comparator verdicts, "
                        "per outcome (promote/rollback)",
                   labels={"verdict": verdict}).inc()


def inc_rollout_transition(to: str) -> None:
    _reg().counter("hvd_serving_rollout_transitions_total",
                   help="rollout state-machine transitions, per "
                        "destination state",
                   labels={"to": to}).inc()


def set_queue_depth(depth: int) -> None:
    _reg().gauge("hvd_serving_queue_depth",
                 help="requests waiting in the dynamic batcher "
                      "queue").set(float(depth))


def set_inflight(n: int) -> None:
    _reg().gauge("hvd_serving_inflight",
                 help="requests admitted and not yet answered "
                      "(router view)").set(float(n))


def set_draining(draining: bool) -> None:
    _reg().gauge("hvd_serving_draining",
                 help="1 while this replica is draining (not "
                      "admitting, finishing in-flight)").set(
        1.0 if draining else 0.0)


def batch_size_buckets(top: Optional[int] = None) -> tuple:
    """Power-of-two batch-size buckets whose top covers ``top`` —
    derived from the configured slot count / batch bound when omitted
    (``HVD_TPU_GEN_SLOTS`` slot arrays can exceed the old fixed top of
    128, which dumped every decode batch into +Inf)."""
    t = top if top else max(env_int("GEN_SLOTS", 4),
                            env_int("SERVING_MAX_BATCH", 8))
    edges = [1]
    while edges[-1] < max(128, t):
        edges.append(edges[-1] * 2)
    return tuple(edges)


def observe_batch(size: int, top: Optional[int] = None) -> None:
    """``top`` — the caller's configured maximum batch (slot count for
    the generate engine, ``max_batch_size`` for the dynamic batcher);
    the registry keeps the FIRST creation's buckets, so the first
    caller's configuration shapes the histogram."""
    _reg().counter("hvd_serving_batches_total",
                   help="forward batches executed by the serving "
                        "loop").inc()
    _reg().histogram("hvd_serving_batch_size",
                     help="formed dynamic-batch sizes",
                     buckets=batch_size_buckets(top)
                     ).observe(float(size))


def observe_latency(seconds: float) -> None:
    _reg().histogram("hvd_serving_latency_seconds",
                     help="end-to-end request latency (admission to "
                          "successful response)",
                     buckets=LATENCY_BUCKETS).observe(seconds)


def set_fleet_gauges(live: int, target: int) -> None:
    _reg().gauge("hvd_serving_replicas_live",
                 help="replica processes currently alive and "
                      "ready").set(float(live))
    _reg().gauge("hvd_serving_replicas_target",
                 help="replica fleet target size").set(float(target))


def inc_replica_exit(outcome: str) -> None:
    """``outcome`` ∈ {``drained``, ``failure``}: a DRAINED exit is a
    planned event (preemption/autopilot drain) and never counts as
    failure evidence against the slot."""
    _reg().counter("hvd_serving_replica_exits_total",
                   help="replica process exits, per classification "
                        "(drained=planned, failure=crash/kill)",
                   labels={"outcome": outcome}).inc()


def inc_respawn() -> None:
    _reg().counter("hvd_serving_replica_respawns_total",
                   help="replacement replicas spawned to heal the "
                        "fleet back to target size").inc()


# ---------------------------------------------------------------------------
# Generative decode engine (horovod_tpu/serving/generate/)
# ---------------------------------------------------------------------------
#: TTFT/ITL buckets: inter-token latency bottoms out well under the
#: request-latency buckets' floor on a warm decode step
GEN_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def observe_prefill(seconds: float) -> None:
    _reg().counter("hvd_serving_prefill_seconds_total",
                   help="wall seconds spent in prefill chunks (prompt "
                        "ingestion) by the generate engine").inc(
        max(0.0, float(seconds)))
    _reg().counter("hvd_serving_prefill_chunks_total",
                   help="fixed-size prefill chunks executed").inc()


def count_gen_tokens(n: int) -> None:
    """Every emitted token lands here exactly once — decode steps in
    batches, plus the single token the LAST prefill chunk emits (it is
    a real emission; leaving it out under-counts by one per request)."""
    if n > 0:
        _reg().counter("hvd_serving_gen_tokens_total",
                       help="tokens emitted by the generate engine "
                            "across all sequences").inc(float(n))


def observe_decode(seconds: float, batch_tokens: int) -> None:
    _reg().counter("hvd_serving_decode_seconds_total",
                   help="wall seconds spent in batched decode steps by "
                        "the generate engine").inc(
        max(0.0, float(seconds)))
    _reg().counter("hvd_serving_decode_steps_total",
                   help="batched decode steps executed (one jit call "
                        "over the full slot array)").inc()
    count_gen_tokens(batch_tokens)


def set_slot_occupancy(occupied: int, total: int) -> None:
    _reg().gauge("hvd_serving_slot_occupancy",
                 help="fraction of decode slots holding a live "
                      "sequence (occupied / total)").set(
        occupied / total if total else 0.0)


def set_gen_waiting(n: int) -> None:
    _reg().gauge("hvd_serving_gen_waiting",
                 help="generate requests admitted past the queue but "
                      "still waiting for a slot + pages").set(float(n))


def set_kv_pool(in_use: int, total: int, page_bytes: int) -> None:
    _reg().gauge("hvd_serving_kv_pages_in_use",
                 help="KV-cache pages currently owned by live "
                      "sequences").set(float(in_use))
    _reg().gauge("hvd_serving_kv_pages_total",
                 help="KV-cache page pool capacity under the active "
                      "plan").set(float(total))
    _reg().gauge("hvd_serving_kv_page_bytes",
                 help="bytes one KV page holds (K+V, all layers) under "
                      "the active plan").set(float(page_bytes))


def observe_ttft(seconds: float) -> None:
    _reg().histogram("hvd_serving_ttft_seconds",
                     help="time to first token: submit to first "
                          "emitted token",
                     buckets=GEN_LATENCY_BUCKETS).observe(float(seconds))


def observe_itl(seconds: float) -> None:
    _reg().histogram("hvd_serving_itl_seconds",
                     help="inter-token latency between consecutive "
                          "emissions of one sequence",
                     buckets=GEN_LATENCY_BUCKETS).observe(float(seconds))


def inc_gen_finished(reason: str) -> None:
    """``reason`` ∈ {``length`` (hit max_new), ``deadline``,
    ``error``, ``drain``}."""
    _reg().counter("hvd_serving_gen_finished_total",
                   help="generate sequences finished, per reason "
                        "(length=hit max_new, deadline, error, drain)",
                   labels={"reason": reason}).inc()


#: THE one nearest-rank quantile — canonical implementation lives in
#: :mod:`horovod_tpu.serving.ledger` (the SLO plane, the rollout
#: comparator and ``ci/check_bench.py --serving`` all share it, so
#: "p99" means the same thing everywhere)
percentile = ledger.quantile


class LatencyWindow:
    """Windowed latency/percentile tracker (one per router, feeding the
    fleet SLO plane).

    ``observe()`` per completed request — with its stage ledger when
    the request path carried one; every ``HVD_TPU_SERVING_WINDOW_S``
    (default 5s) the closing window publishes ``hvd_serving_p50/p99
    _seconds`` + ``hvd_serving_qps`` + ``hvd_serving_stage_share``
    gauges, records a ``{"serving": {...}}`` time-series point carrying
    the stage breakdown, pushes the window's worst requests into the
    tail-exemplar ring, and — when ``HVD_TPU_SERVING_SLO_P99_MS`` is
    set (> 0) — runs the multi-window burn-rate SLO check
    (:class:`horovod_tpu.serving.ledger.BurnRateSlo`: one ``slo_breach``
    finding per episode, naming the dominant stage).  The closed doc is
    also fed to the anomaly engine's serving detectors (``ttft_drift``,
    ``queue_growth``, ``kv_thrash``)."""

    def __init__(self, window_s: Optional[float] = None,
                 ring: Optional[ledger.ExemplarRing] = None) -> None:
        self.window_s = window_s if window_s is not None \
            else env_float("SERVING_WINDOW_S", 5.0)
        self.slo = ledger.BurnRateSlo()
        self.slo_p99_s = self.slo.slo_p99_s
        self._ring = ring if ring is not None else ledger.default_ring()
        self._lock = threading.Lock()
        self._lat: List[float] = []
        self._shed = 0
        self._bad = 0
        self._books = ledger.WindowBooks()
        self._opened = time.monotonic()

    def observe(self, seconds: float,
                stages: Optional[dict] = None,
                trace: Optional[str] = None,
                req_id: Optional[str] = None,
                version: Optional[int] = None,
                ttft_s: Optional[float] = None) -> None:
        observe_latency(seconds)
        if stages:
            ledger.observe_stage_seconds(
                ledger.close_books(seconds, stages))
        with self._lock:
            self._lat.append(seconds)
            if self.slo.is_bad(seconds):
                self._bad += 1
            self._books.add(seconds, stages, trace=trace,
                            req_id=req_id, version=version,
                            ttft_s=ttft_s)
        self.maybe_roll()

    def note_shed(self) -> None:
        with self._lock:
            self._shed += 1

    def maybe_roll(self, force: bool = False) -> Optional[dict]:
        """Close the window if its time is up (or ``force``); returns
        the window summary when one closed."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._opened < self.window_s:
                return None
            lat, shed, bad = self._lat, self._shed, self._bad
            elapsed = max(now - self._opened, 1e-9)
            self._lat, self._shed, self._bad = [], 0, 0
            stage_doc, exemplars = self._books.close()
            self._opened = now
        lat.sort()
        doc = {
            "window_s": round(elapsed, 3),
            "requests": len(lat),
            "qps": round(len(lat) / elapsed, 3),
            "p50_s": round(percentile(lat, 0.50), 6),
            "p99_s": round(percentile(lat, 0.99), 6),
            "shed": shed,
        }
        if self.slo.enabled:
            doc["slo_bad"] = bad
        doc.update(stage_doc)
        reg = _reg()
        reg.gauge("hvd_serving_qps",
                  help="completed requests per second over the last "
                       "closed window").set(doc["qps"])
        reg.gauge("hvd_serving_p50_seconds",
                  help="windowed median request latency").set(doc["p50_s"])
        reg.gauge("hvd_serving_p99_seconds",
                  help="windowed p99 request latency — the serving SLO "
                       "signal").set(doc["p99_s"])
        # every canonical stage publishes each roll (absent -> 0.0), so
        # an idle window zeroes the shares instead of freezing them
        ledger.publish_stage_shares(doc.get("stage_shares") or {})
        for ex in exemplars:
            self._ring.add(ex)
        try:
            from horovod_tpu.metrics.timeseries import record_point
            record_point({"serving": doc})
        except Exception:
            pass
        self.slo.observe_window(doc["requests"], bad, doc)
        try:
            from horovod_tpu.metrics.anomaly import observe_serving_window
            observe_serving_window(doc)
        except Exception:
            pass
        return doc
