"""Serving-plane metrics: per-request latency SLOs on the fleet plane.

Every number the zero-drop guarantee is proven from lives here
(docs/SERVING.md): request admission/completion/shed counters (a shed
is EXPLICIT — counted and answered 429, never a silent drop), hedge and
retry counters, queue/inflight gauges, the latency histogram, and a
windowed percentile tracker that publishes ``hvd_serving_p50/p99``
gauges, records one ``{"serving": ...}`` point per window into the
step time-series store (rendered by ``python -m horovod_tpu.metrics
history --serving``), and reports an ``slo_breach`` anomaly finding
when the windowed p99 stays over ``HVD_TPU_SERVING_SLO_P99_MS`` —
which the autopilot's ``serving-slo-scaleout`` policy turns into a
fleet scale-out (docs/OBSERVABILITY.md "Autopilot").
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.metrics.registry import default_registry

#: latency buckets: serving answers in milliseconds, not the step-time
#: seconds the default buckets are shaped for
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _reg():
    return default_registry()


def inc_accepted() -> None:
    _reg().counter("hvd_serving_accepted_total",
                   help="requests admitted past the router's "
                        "admission control").inc()


def inc_completed() -> None:
    _reg().counter("hvd_serving_completed_total",
                   help="accepted requests answered with exactly one "
                        "successful response").inc()


def inc_failed() -> None:
    _reg().counter("hvd_serving_failed_total",
                   help="accepted requests that exhausted every "
                        "retry/hedge before their deadline").inc()


def inc_shed(where: str) -> None:
    """An EXPLICIT load-shed (429): ``where`` names the backpressure
    point — ``admission`` (router inflight budget), ``queue`` (replica
    batch queue full), ``deadline`` (expired before compute),
    ``draining`` (replica refusing new work), ``chaos`` (injected)."""
    _reg().counter("hvd_serving_shed_total",
                   help="requests explicitly load-shed (429), per "
                        "backpressure point",
                   labels={"where": where}).inc()


def inc_hedged() -> None:
    _reg().counter("hvd_serving_hedged_total",
                   help="hedge requests launched at a second replica "
                        "after the hedge timeout").inc()


def inc_retried() -> None:
    _reg().counter("hvd_serving_retried_total",
                   help="requests re-dispatched to a surviving replica "
                        "after a replica error/death").inc()


def inc_swap() -> None:
    _reg().counter("hvd_serving_swaps_total",
                   help="zero-downtime hot weight swaps applied from "
                        "the durable sharded store").inc()


def set_weight_version(step: int) -> None:
    _reg().gauge("hvd_serving_weight_version",
                 help="durable-store step of the weights currently "
                      "serving").set(float(step))


def inc_weight_swap(reason: str) -> None:
    """Every ``(version, params)`` flip lands here once, per cause —
    ``chase`` (the swapper following the store's latest commit),
    ``pin`` (a rollout controller pinning a candidate/incumbent) or
    ``rollback`` (repin to the incumbent during an auto-rollback).  The
    weight version gauge alone cannot show a BACKWARD move after the
    fact; this counter plus the ``weight_swap`` flight event are what
    the autopsy reads the rollback from."""
    _reg().counter("hvd_serving_weight_swaps_total",
                   help="weight-version flips, per cause (chase=follow "
                        "latest commit, pin=rollout pin, "
                        "rollback=repin to incumbent)",
                   labels={"reason": reason}).inc()


# ---------------------------------------------------------------------------
# Canary weight rollout (horovod_tpu/serving/rollout/)
# ---------------------------------------------------------------------------
#: rollout state machine positions, as published on the state gauge
ROLLOUT_STATES = ("idle", "canary", "expanding", "promoted",
                  "rolling_back", "rolled_back")


def set_rollout_state(state: str) -> None:
    _reg().gauge("hvd_serving_rollout_state",
                 help="rollout state machine position (0=idle, "
                      "1=canary, 2=expanding, 3=promoted, "
                      "4=rolling_back, 5=rolled_back)").set(
        float(ROLLOUT_STATES.index(state))
        if state in ROLLOUT_STATES else -1.0)


def set_rollout_canary_pct(pct: float) -> None:
    _reg().gauge("hvd_serving_rollout_canary_pct",
                 help="traffic percentage currently routed to the "
                      "candidate weight version (0 = no active "
                      "split)").set(float(pct))


def inc_rollout_verdict(verdict: str) -> None:
    _reg().counter("hvd_serving_rollout_verdicts_total",
                   help="per-version SLO/quality comparator verdicts, "
                        "per outcome (promote/rollback)",
                   labels={"verdict": verdict}).inc()


def inc_rollout_transition(to: str) -> None:
    _reg().counter("hvd_serving_rollout_transitions_total",
                   help="rollout state-machine transitions, per "
                        "destination state",
                   labels={"to": to}).inc()


def set_queue_depth(depth: int) -> None:
    _reg().gauge("hvd_serving_queue_depth",
                 help="requests waiting in the dynamic batcher "
                      "queue").set(float(depth))


def set_inflight(n: int) -> None:
    _reg().gauge("hvd_serving_inflight",
                 help="requests admitted and not yet answered "
                      "(router view)").set(float(n))


def set_draining(draining: bool) -> None:
    _reg().gauge("hvd_serving_draining",
                 help="1 while this replica is draining (not "
                      "admitting, finishing in-flight)").set(
        1.0 if draining else 0.0)


def observe_batch(size: int) -> None:
    _reg().counter("hvd_serving_batches_total",
                   help="forward batches executed by the serving "
                        "loop").inc()
    _reg().histogram("hvd_serving_batch_size",
                     help="formed dynamic-batch sizes",
                     buckets=(1, 2, 4, 8, 16, 32, 64, 128)
                     ).observe(float(size))


def observe_latency(seconds: float) -> None:
    _reg().histogram("hvd_serving_latency_seconds",
                     help="end-to-end request latency (admission to "
                          "successful response)",
                     buckets=LATENCY_BUCKETS).observe(seconds)


def set_fleet_gauges(live: int, target: int) -> None:
    _reg().gauge("hvd_serving_replicas_live",
                 help="replica processes currently alive and "
                      "ready").set(float(live))
    _reg().gauge("hvd_serving_replicas_target",
                 help="replica fleet target size").set(float(target))


def inc_replica_exit(outcome: str) -> None:
    """``outcome`` ∈ {``drained``, ``failure``}: a DRAINED exit is a
    planned event (preemption/autopilot drain) and never counts as
    failure evidence against the slot."""
    _reg().counter("hvd_serving_replica_exits_total",
                   help="replica process exits, per classification "
                        "(drained=planned, failure=crash/kill)",
                   labels={"outcome": outcome}).inc()


def inc_respawn() -> None:
    _reg().counter("hvd_serving_replica_respawns_total",
                   help="replacement replicas spawned to heal the "
                        "fleet back to target size").inc()


# ---------------------------------------------------------------------------
# Generative decode engine (horovod_tpu/serving/generate/)
# ---------------------------------------------------------------------------
#: TTFT/ITL buckets: inter-token latency bottoms out well under the
#: request-latency buckets' floor on a warm decode step
GEN_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def observe_prefill(seconds: float) -> None:
    _reg().counter("hvd_serving_prefill_seconds_total",
                   help="wall seconds spent in prefill chunks (prompt "
                        "ingestion) by the generate engine").inc(
        max(0.0, float(seconds)))
    _reg().counter("hvd_serving_prefill_chunks_total",
                   help="fixed-size prefill chunks executed").inc()


def count_gen_tokens(n: int) -> None:
    """Every emitted token lands here exactly once — decode steps in
    batches, plus the single token the LAST prefill chunk emits (it is
    a real emission; leaving it out under-counts by one per request)."""
    if n > 0:
        _reg().counter("hvd_serving_gen_tokens_total",
                       help="tokens emitted by the generate engine "
                            "across all sequences").inc(float(n))


def observe_decode(seconds: float, batch_tokens: int) -> None:
    _reg().counter("hvd_serving_decode_seconds_total",
                   help="wall seconds spent in batched decode steps by "
                        "the generate engine").inc(
        max(0.0, float(seconds)))
    _reg().counter("hvd_serving_decode_steps_total",
                   help="batched decode steps executed (one jit call "
                        "over the full slot array)").inc()
    count_gen_tokens(batch_tokens)


def set_slot_occupancy(occupied: int, total: int) -> None:
    _reg().gauge("hvd_serving_slot_occupancy",
                 help="fraction of decode slots holding a live "
                      "sequence (occupied / total)").set(
        occupied / total if total else 0.0)


def set_gen_waiting(n: int) -> None:
    _reg().gauge("hvd_serving_gen_waiting",
                 help="generate requests admitted past the queue but "
                      "still waiting for a slot + pages").set(float(n))


def set_kv_pool(in_use: int, total: int, page_bytes: int) -> None:
    _reg().gauge("hvd_serving_kv_pages_in_use",
                 help="KV-cache pages currently owned by live "
                      "sequences").set(float(in_use))
    _reg().gauge("hvd_serving_kv_pages_total",
                 help="KV-cache page pool capacity under the active "
                      "plan").set(float(total))
    _reg().gauge("hvd_serving_kv_page_bytes",
                 help="bytes one KV page holds (K+V, all layers) under "
                      "the active plan").set(float(page_bytes))


def observe_ttft(seconds: float) -> None:
    _reg().histogram("hvd_serving_ttft_seconds",
                     help="time to first token: submit to first "
                          "emitted token",
                     buckets=GEN_LATENCY_BUCKETS).observe(float(seconds))


def observe_itl(seconds: float) -> None:
    _reg().histogram("hvd_serving_itl_seconds",
                     help="inter-token latency between consecutive "
                          "emissions of one sequence",
                     buckets=GEN_LATENCY_BUCKETS).observe(float(seconds))


def inc_gen_finished(reason: str) -> None:
    """``reason`` ∈ {``length`` (hit max_new), ``deadline``,
    ``error``, ``drain``}."""
    _reg().counter("hvd_serving_gen_finished_total",
                   help="generate sequences finished, per reason "
                        "(length=hit max_new, deadline, error, drain)",
                   labels={"reason": reason}).inc()


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted list — THE one
    implementation (the bench artifact's p99 and the SLO plane's p99
    must mean the same thing, `ci/check_bench.py --serving` compares
    them)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class LatencyWindow:
    """Windowed latency/percentile tracker (one per router, feeding the
    fleet SLO plane).

    ``observe()`` per completed request; every ``HVD_TPU_SERVING_WINDOW_S``
    (default 5s) the closing window publishes ``hvd_serving_p50/p99
    _seconds`` + ``hvd_serving_qps`` gauges, records a ``{"serving":
    {...}}`` time-series point, and — when ``HVD_TPU_SERVING_SLO_P99_MS``
    is set (> 0) — checks the SLO: ``HVD_TPU_SERVING_SLO_WINDOWS``
    (default 2) consecutive breaching windows report ONE ``slo_breach``
    anomaly finding (hysteresis mirrors the anomaly engine's: one
    finding per episode, re-armed after a healthy window)."""

    def __init__(self, window_s: Optional[float] = None) -> None:
        self.window_s = window_s if window_s is not None \
            else env_float("SERVING_WINDOW_S", 5.0)
        self.slo_p99_s = env_float("SERVING_SLO_P99_MS", 0.0) / 1000.0
        self.slo_windows = max(1, env_int("SERVING_SLO_WINDOWS", 2))
        self._lock = threading.Lock()
        self._lat: List[float] = []
        self._shed = 0
        self._opened = time.monotonic()
        self._breach_streak = 0
        self._breach_active = False

    def observe(self, seconds: float) -> None:
        observe_latency(seconds)
        with self._lock:
            self._lat.append(seconds)
        self.maybe_roll()

    def note_shed(self) -> None:
        with self._lock:
            self._shed += 1

    def maybe_roll(self, force: bool = False) -> Optional[dict]:
        """Close the window if its time is up (or ``force``); returns
        the window summary when one closed."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._opened < self.window_s:
                return None
            lat, shed = self._lat, self._shed
            elapsed = max(now - self._opened, 1e-9)
            self._lat, self._shed = [], 0
            self._opened = now
        lat.sort()
        doc = {
            "window_s": round(elapsed, 3),
            "requests": len(lat),
            "qps": round(len(lat) / elapsed, 3),
            "p50_s": round(percentile(lat, 0.50), 6),
            "p99_s": round(percentile(lat, 0.99), 6),
            "shed": shed,
        }
        reg = _reg()
        reg.gauge("hvd_serving_qps",
                  help="completed requests per second over the last "
                       "closed window").set(doc["qps"])
        reg.gauge("hvd_serving_p50_seconds",
                  help="windowed median request latency").set(doc["p50_s"])
        reg.gauge("hvd_serving_p99_seconds",
                  help="windowed p99 request latency — the serving SLO "
                       "signal").set(doc["p99_s"])
        try:
            from horovod_tpu.metrics.timeseries import record_point
            record_point({"serving": doc})
        except Exception:
            pass
        self._check_slo(doc)
        return doc

    def _check_slo(self, doc: dict) -> None:
        if self.slo_p99_s <= 0:
            return
        if not doc["requests"]:
            # an idle window is not a breach — and a breach episode
            # does not survive the traffic that caused it
            self._breach_streak = 0
            self._breach_active = False
            return
        if doc["p99_s"] > self.slo_p99_s:
            self._breach_streak += 1
            if self._breach_streak >= self.slo_windows \
                    and not self._breach_active:
                self._breach_active = True
                try:
                    from horovod_tpu.metrics.anomaly import report_finding
                    report_finding(
                        "slo_breach", p99_s=doc["p99_s"],
                        slo_s=self.slo_p99_s, qps=doc["qps"],
                        shed=doc["shed"],
                        consecutive=self._breach_streak)
                except Exception:
                    pass
        else:
            self._breach_streak = 0
            self._breach_active = False
