"""``python -m horovod_tpu.serving`` — run a local serving stack.

Operator entry point (docs/SERVING.md "Running a local fleet"): spawns
``--replicas`` replica processes over ``--store-dir``, wires them
behind an in-process router, and serves the FRONT on ``--port``:

* ``POST /infer`` — ``{"id": ..., "x": [...]}`` through the router
  (admission control, hedging, retry); sheds answer 429 explicitly.
* ``GET /readyz`` — 200 once the fleet serves at least one READY
  replica; ``/healthz`` — process liveness + fleet view.
* ``GET /metrics`` — the front process's registry: the router-side
  ``hvd_serving_*`` counters/gauges (qps, p50/p99, shed/hedge/retry,
  fleet size) that ``python -m horovod_tpu.metrics top`` renders as
  the SERVING line.

Intended for local smoke-serving and the bench; production runs embed
:class:`ReplicaFleet`/:class:`Router` behind their own front end.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler


class _FrontHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass

    def _send(self, code: int, doc: dict) -> None:
        try:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            pass

    def do_POST(self):
        from horovod_tpu.serving.batcher import SheddedError
        from horovod_tpu.serving.router import (RequestFailed,
                                                RequestRejected)
        if self.path.split("?", 1)[0].rstrip("/") != "/infer":
            self._send(404, {"error": "not found"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n))
        except (ValueError, OSError):
            self._send(400, {"error": "bad request body"})
            return
        router = self.server.router
        from horovod_tpu import tracing
        trace = tracing.decode(self.headers.get(tracing.TRACEPARENT))
        try:
            resp = router.submit(doc.get("x"), req_id=doc.get("id"),
                                 deadline_s=(float(doc["deadline_ms"])
                                             / 1000.0
                                             if "deadline_ms" in doc
                                             else None),
                                 trace=trace)
            self._send(200, resp)
        except SheddedError as e:
            self._send(429, {"error": str(e)})
        except RequestRejected as e:
            self._send(e.code, e.doc)  # the replica's own 4xx verdict
        except RequestFailed as e:
            self._send(503, {"error": str(e)})
        except Exception as e:  # the front must not die per request
            self._send(500, {"error": repr(e)})

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        fleet = self.server.fleet
        if path == "/metrics":
            from horovod_tpu.metrics.registry import (default_registry,
                                                      render_prometheus)
            body = render_prometheus(default_registry().snapshot())
            try:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except OSError:
                pass
        elif path == "/readyz":
            live = fleet.live_count()
            self._send(200 if live > 0 else 503,
                       {"ready": live > 0, "replicas_live": live,
                        "replicas_target": fleet.target})
        elif path == "/healthz":
            self._send(200, {"status": "ok",
                             "replicas_live": fleet.live_count(),
                             "replicas_target": fleet.target})
        else:
            self._send(404, {"error": "not found"})


def _rollout_main(argv) -> int:
    """``python -m horovod_tpu.serving rollout status --store-dir D`` —
    the stuck-rollout runbook's first stop (docs/SERVING.md "Canary
    rollout"): print the controller's durably persisted status doc
    (state, canary slots, split, transition history, trace id) from
    OUTSIDE the controller process."""
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serving rollout")
    p.add_argument("command", choices=["status"])
    p.add_argument("--store-dir", required=True,
                   help="the store the rollout persists its status "
                        "next to")
    args = p.parse_args(argv)
    from horovod_tpu.serving.rollout import read_status
    doc = read_status(args.store_dir)
    if doc is None:
        print(f"rollout: no status recorded under {args.store_dir!r} "
              "(no rollout ever ran against this store)")
        return 1
    print(json.dumps(doc, indent=1))
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "rollout":
        return _rollout_main(argv[1:])
    p = argparse.ArgumentParser(prog="python -m horovod_tpu.serving")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--port", type=int, default=0,
                   help="front port for POST /infer + /metrics "
                        "(0 = ephemeral, printed at startup)")
    p.add_argument("--store-dir", default=None,
                   help="durable sharded store to serve (and hot-swap) "
                        "weights from")
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--status-interval", type=float, default=5.0)
    p.add_argument("--duration", type=float, default=0.0,
                   help="exit after this many seconds (0 = forever)")
    args = p.parse_args(argv)

    # crash hooks: with HVD_TPU_FLIGHT_DUMP_ON_EXIT=1 the front's
    # flight ring (router request/dispatch trace spans) lands as a
    # dump next to the replicas' — the merged timeline's router track
    from horovod_tpu.diagnostics.flight_recorder import \
        install_crash_hooks
    install_crash_hooks()
    from horovod_tpu.runner.http_kv import ThreadedHTTPServer
    from horovod_tpu.serving import ReplicaFleet, Router
    fleet = ReplicaFleet(size=args.replicas, store_dir=args.store_dir,
                         dim=args.dim).start()
    router = Router(fleet.endpoints)
    fleet.register_autopilot_hook()
    # handler pool sized from the ADMISSION budget (same rule the
    # replica applies to itself): the router's explicit 429 shed must
    # be reachable — a pool smaller than max_inflight would answer raw
    # 503 busy before admission control ever engaged.  An explicit
    # HVD_TPU_HTTP_MAX_HANDLERS wins verbatim (0 = unbounded).
    from horovod_tpu.common.config import env_int
    env_pool = env_int("HTTP_MAX_HANDLERS", -1)
    pool = env_pool if env_pool >= 0 else router.max_inflight + 16
    front = ThreadedHTTPServer(("0.0.0.0", args.port), _FrontHandler,
                               max_handlers=pool)
    front.router, front.fleet = router, fleet
    threading.Thread(target=front.serve_forever,
                     name="hvd-serving-front", daemon=True).start()
    print(f"serving: front on :{front.server_address[1]}/infer, "
          f"{args.replicas} replicas {fleet.endpoints()}", flush=True)
    start = time.monotonic()
    try:
        while not args.duration \
                or time.monotonic() - start < args.duration:
            time.sleep(args.status_interval)
            acct = router.accounting()
            print(f"serving: live={fleet.live_count()}/{fleet.target} "
                  f"outcomes={acct['outcomes']}", flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        front.shutdown()
        router.close()
        fleet.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
