"""One serving replica: HTTP front, dynamic batcher, compiled forward
loop, hot weight swap, and drain-to-DRAINED semantics.

A replica is one worker process (spawned and healed by
:class:`horovod_tpu.serving.fleet.ReplicaFleet`, or embedded in-process
for tests/bench) that:

* answers ``POST /infer`` by admitting the request into the bounded
  :class:`~horovod_tpu.serving.batcher.DynamicBatcher` and blocking the
  handler until the serving loop fulfills it (the handler threads are
  the continuation — the hardened :class:`ThreadedHTTPServer`'s bounded
  pool is the concurrency limit);
* runs ONE serving loop thread pulling formed batches, padding them to
  the fixed ``max_batch_size`` (a single compiled forward — batch-size
  churn must not recompile), and executing the jit'd ``apply_fn``;
* **hot weight swap** (docs/SERVING.md "Hot weight swap"): a swapper
  thread polls the durable sharded store
  (:class:`horovod_tpu.checkpoint.ShardedCheckpointer`) for commits
  newer than the serving version, restores them ONTO THE SERVING MESH
  (``restore_latest`` reshards — the training world's size is
  irrelevant) while the old weights keep serving, then flips the
  ``(version, params)`` pair atomically between batches.  A corrupt
  newest commit falls back to the next-older one (store semantics) —
  the replica never serves a half-loaded version;
* **drains** on a chaos/maintenance ``preemption`` notice, SIGTERM (in
  ``main()``), or ``POST /drain``: admission stops instantly
  (``/readyz`` → 503 so routers stop sending; new submits get
  :class:`DrainingError`), every in-flight request is answered, then
  the replica reports DRAINED — and, under ``main()``, exits 0, which
  the fleet classifies as a planned exit (never failure evidence);
* is **idempotent** per request id: a bounded response cache plus an
  in-flight table mean a hedged/retried duplicate of a request that
  already ran (or is running) returns the SAME response instead of
  recomputing — the router may fan a request out freely.

``/readyz`` readiness = model loaded AND queue depth under budget AND
not draining; ``/healthz`` liveness = process up + serving loop alive.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, Optional

import numpy as np

from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.common.logging import get_logger
from horovod_tpu.runner.http_kv import ThreadedHTTPServer
from horovod_tpu.serving import metrics as smetrics
from horovod_tpu.serving.batcher import (DeadlineError, DrainingError,
                                         DynamicBatcher, SheddedError)


def _flight(kind: str, **fields) -> None:
    try:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(kind, **fields)
    except Exception:
        pass


# -- demo model ---------------------------------------------------------------
def demo_params(dim: int = 16, scale: float = 1.0) -> dict:
    """Deterministic tiny affine model — the serving analog of the
    bench's synthetic models.  ``scale`` distinguishes weight VERSIONS
    observably: ``y = scale * mean(x) + bias`` per output lane."""
    return {
        "w": np.full((dim, dim), scale / dim, dtype=np.float32),
        "b": np.zeros((dim,), dtype=np.float32),
    }


def demo_apply(params: dict, x):
    import jax.numpy as jnp
    return jnp.dot(x, params["w"]) + params["b"]


# -- the replica --------------------------------------------------------------
class ReplicaServer:
    """One replica: HTTP server + batcher + serving loop + swapper.

    Args:
      apply_fn: ``(params, X[batch, ...]) -> Y[batch, ...]``; jit'd
        here.  Default: the demo affine model.
      params: initial weights pytree (ignored when ``store_dir``
        already holds a commit — the store is the source of truth).
      store_dir: durable sharded store to restore from / watch for hot
        swaps (None = static weights).
      dim: demo-model width (used when no params and no store commit).
      port: HTTP port (0 = ephemeral).
      replica_id: name stamped into responses/flight events.
    """

    def __init__(self, apply_fn: Optional[Callable] = None,
                 params: Any = None, store_dir: Optional[str] = None,
                 dim: int = 16, port: int = 0, replica_id: str = "r0",
                 batcher: Optional[DynamicBatcher] = None,
                 swap_poll_s: Optional[float] = None,
                 mode: str = "infer", gen_model: Any = None,
                 pin_version: Optional[int] = None) -> None:
        self.replica_id = replica_id
        self.dim = dim
        # version pinning (docs/SERVING.md "Canary rollout"): while
        # pinned, the swapper serves EXACTLY this durable-store step —
        # it never chases a newer commit (that is how a canary holds
        # the candidate while the rest of the fleet holds the
        # incumbent, and how a rollback repins without a restart).
        # ``pin_version`` at construction restores the pinned step
        # directly, so a healed replacement never transits through
        # whatever happens to be latest.
        self._pin: Optional[int] = None if pin_version is None \
            else int(pin_version)
        # generate mode: a continuous-batching decode engine rides
        # alongside the request-level path (POST /generate; the /infer
        # plumbing stays untouched).  ``gen_model`` is a (params, cfg)
        # pair; None = the deterministic demo transformer.
        self.mode = mode
        self.engine = None
        if mode == "generate":
            from horovod_tpu.serving.generate import (GenerateEngine,
                                                      demo_gen_setup)
            g_params, g_cfg = gen_model if gen_model is not None \
                else demo_gen_setup()
            self.engine = GenerateEngine(g_params, g_cfg)
        self._apply_fn = apply_fn or demo_apply
        self._store_dir = store_dir
        self._swap_poll_s = swap_poll_s if swap_poll_s is not None \
            else env_float("SERVING_SWAP_POLL_S", 1.0)
        self.batcher = batcher or DynamicBatcher()
        self._ready_queue_max = env_int(
            "SERVING_READY_QUEUE", max(1, int(self.batcher.max_queue * 0.9)))
        self._params_lock = threading.Lock()
        self._params = params
        self._version = 0
        self._compiled = None
        self._model_loaded = False
        self._stop = threading.Event()
        self._drained_event = threading.Event()
        self._drain_source: Optional[str] = None
        self._loop_alive = False
        # idempotency: answered requests (bounded LRU) + in-flight table
        self._resp_cache: OrderedDict = OrderedDict()
        self._resp_cache_max = env_int("SERVING_IDEMPOTENCY_CACHE", 4096)
        self._pending: dict = {}
        self._pending_lock = threading.Lock()
        self._threads = []
        # handler pool sized FROM the admission budget: every queued +
        # in-batch request holds a handler thread awaiting its result,
        # and the pool must exceed that so (a) the batcher's explicit
        # queue shed is reachable over HTTP (a pool smaller than the
        # queue 503s before the 429 path can answer) and (b) readiness
        # probes / metrics scrapes are not starved by a full queue.
        # An EXPLICIT HVD_TPU_HTTP_MAX_HANDLERS wins verbatim — incl.
        # the documented 0 = unbounded — over the derived size.
        env_pool = env_int("HTTP_MAX_HANDLERS", -1)
        pool = env_pool if env_pool >= 0 else (
            self.batcher.max_queue + 2 * self.batcher.max_batch_size
            + 16)
        self._httpd = ThreadedHTTPServer(("0.0.0.0", port),
                                         _ReplicaHandler,
                                         max_handlers=pool)
        self._httpd.replica = self

    # -- lifecycle ----------------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "ReplicaServer":
        self._load_initial_weights()
        for name, target in (
                ("serve-loop", self._serve_loop),
                ("swapper", self._swap_loop),
                ("preempt-watch", self._preemption_loop)):
            t = threading.Thread(target=target,
                                 name=f"hvd-serving-{name}", daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="hvd-serving-http", daemon=True)
        t.start()
        self._threads.append(t)
        if self.engine is not None:
            self.engine.start()
        _flight("serving_replica_start", replica=self.replica_id,
                port=self.port, version=self._version)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.engine is not None:
            self.engine.stop()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass

    # -- weights ------------------------------------------------------------
    def _store(self):
        from horovod_tpu.checkpoint import ShardedCheckpointer
        # world_size=1: the serving mesh is THIS replica; restore
        # reshards whatever world wrote the commit onto it
        return ShardedCheckpointer(self._store_dir, rank=0, world_size=1)

    @staticmethod
    def _extract_params(doc: Any) -> Any:
        """A durable commit is usually an elastic-state dict; serve its
        ``params`` leaf when present, the whole doc otherwise."""
        if isinstance(doc, dict) and "params" in doc:
            return doc["params"]
        return doc

    def _load_initial_weights(self) -> None:
        if self._store_dir:
            try:
                store = self._store()
                if self._pin is not None:
                    # a pinned spawn (fleet heal during a rollout)
                    # restores THE pinned step: the replacement joins
                    # the fleet at its slot's assigned version, never
                    # at whatever commit happens to be newest
                    doc = store.restore(self._pin)
                    self._set_params(self._extract_params(doc),
                                     version=self._pin, swap=False)
                else:
                    # return_step: on a corrupt-newest fallback the
                    # state is OLDER than latest_step(), and the
                    # serving version must name the weights actually
                    # loaded
                    step, doc = store.restore_latest(return_step=True)
                    if step is not None:
                        self._set_params(self._extract_params(doc),
                                         version=int(step), swap=False)
            except Exception:
                get_logger().warning(
                    "serving: initial restore from %s failed; starting "
                    "with built-in weights", self._store_dir,
                    exc_info=True)
        if self._params is None:
            self._params = demo_params(self.dim)
        self._compile()
        self._model_loaded = True
        smetrics.set_weight_version(self._version)

    def _compile(self) -> None:
        import jax
        self._compiled = jax.jit(self._apply_fn)

    def _set_params(self, params: Any, version: int,
                    swap: bool = True, reason: str = "chase") -> None:
        import jax
        device = jax.tree_util.tree_map(jax.numpy.asarray, params)
        # hold the decode loop at a step boundary for the flip; the
        # held time lands on live sequences' ``swap_pause`` ledger
        # stage (the infer path charges its params-lock wait the same
        # way in _run_batch)
        engine = getattr(self, "engine", None)
        if engine is not None:
            engine.begin_swap()
        try:
            with self._params_lock:
                from_version = self._version
                self._params = device
                self._version = version
        finally:
            if engine is not None:
                engine.end_swap()
        smetrics.set_weight_version(version)
        if swap:
            smetrics.inc_swap()
            smetrics.inc_weight_swap(reason)
            # the gauge alone cannot show a BACKWARD move after the
            # fact — the flight event names both endpoints and the
            # cause, so the autopsy shows the rollback (a backward flip
            # is legitimate exactly when a pin/rollback asked for it,
            # and must never happen silently)
            _flight("weight_swap", replica=self.replica_id,
                    from_version=from_version, to_version=version,
                    reason=reason)
            _flight("serving_swap", replica=self.replica_id,
                    version=version)
            if version < from_version:
                get_logger().warning(
                    "serving: weight version moved BACKWARD %d -> %d "
                    "(replica %s, reason=%s) — expected only during a "
                    "rollout rollback", from_version, version,
                    self.replica_id, reason)
            else:
                get_logger().info(
                    "serving: hot-swapped to weight version %d "
                    "(replica %s, reason=%s)", version,
                    self.replica_id, reason)

    # -- version pinning ----------------------------------------------------
    def pin(self, version: int, reason: str = "pin") -> dict:
        """Pin this replica to durable-store step ``version``: restore
        it now (the same atomic between-batch flip as a hot swap — no
        request is dropped) and stop the swapper from chasing newer
        commits until :meth:`unpin`.  ``reason`` ∈ {``pin``,
        ``rollback``} stamps the ``weight_swap`` audit event."""
        version = int(version)
        if self._store_dir and version != self._version:
            # restore BEFORE committing the pin: a nonexistent/corrupt
            # step raises out of the /pin route (500) with the replica
            # UNPINNED and still serving its old weights — never
            # pinned to an unloadable version that _swap_loop would
            # retry forever while refusing to chase commits
            doc = self._store().restore(version)
            self._set_params(self._extract_params(doc),
                             version=version, reason=reason)
        self._pin = version
        _flight("serving_pin", replica=self.replica_id,
                version=version, reason=reason)
        return {"replica": self.replica_id, "pinned": self._pin,
                "version": self._version}

    def unpin(self) -> dict:
        """Clear the pin; the swapper resumes chasing the store's
        latest commit on its next poll."""
        self._pin = None
        _flight("serving_unpin", replica=self.replica_id,
                version=self._version)
        return {"replica": self.replica_id, "pinned": None,
                "version": self._version}

    @property
    def pinned(self) -> Optional[int]:
        return self._pin

    def _swap_loop(self) -> None:
        if not self._store_dir:
            return
        bad_newest = None  # a newest step whose restore fell back
        while not self._stop.wait(self._swap_poll_s):
            try:
                pin = self._pin
                if pin is not None:
                    # pinned: converge onto the pinned step if a failed
                    # pinned-SPAWN initial restore left us elsewhere
                    # (pin() itself only commits after its restore
                    # succeeds), then HOLD — a pinned replica never
                    # chases the latest commit
                    if self._version != pin:
                        doc = self._store().restore(pin)
                        self._set_params(self._extract_params(doc),
                                         version=pin, reason="pin")
                    continue
                store = self._store()
                step = store.latest_step()
                if step is None or step <= self._version \
                        or step == bad_newest:
                    continue
                # restore while the OLD weights keep serving; flip is
                # the lock-guarded pointer swap above — between batches.
                # return_step: a corrupt newest FALLS BACK to an older
                # commit (store semantics) — the version must name the
                # weights actually restored, and a fallback onto what
                # we already serve is NOT a swap (remember the bad
                # newest so each poll doesn't re-pay the failed
                # restore; a NEWER commit landing later clears it)
                restored, doc = store.restore_latest(return_step=True)
                if restored is not None and restored > self._version:
                    self._set_params(self._extract_params(doc),
                                     version=int(restored))
                    bad_newest = None
                else:
                    bad_newest = step
            except Exception:
                get_logger().warning(
                    "serving: weight-swap poll failed; still serving "
                    "version %d", self._version, exc_info=True)

    # -- drain --------------------------------------------------------------
    def drain(self, source: str = "admin") -> None:
        """Stop admitting, finish in-flight, then report DRAINED.  The
        actual exit is the embedder's call (``main()`` exits 0)."""
        if self.batcher.draining:
            return
        self._drain_source = source
        _flight("serving_drain_begin", replica=self.replica_id,
                source=source)
        get_logger().warning("serving: replica %s draining (%s)",
                             self.replica_id, source)
        self.batcher.drain()
        if self.engine is not None:
            self.engine.drain()

        def _finish():
            timeout_s = env_float("SERVING_DRAIN_TIMEOUT_S", 30.0)
            ok = self.batcher.wait_drained(timeout_s=timeout_s)
            if self.engine is not None:
                # drained = every admitted SEQUENCE answered, not just
                # the admission queue emptied (the engine hands off to
                # the slot scheduler long before tokens finish)
                ok = self.engine.wait_drained(timeout_s=timeout_s) and ok
            _flight("serving_drained", replica=self.replica_id,
                    source=source, clean=ok)
            self._drained_event.set()

        threading.Thread(target=_finish, name="hvd-serving-drain",
                         daemon=True).start()

    @property
    def draining(self) -> bool:
        return self.batcher.draining

    def drained(self) -> bool:
        return self._drained_event.is_set()

    def wait_drained(self, timeout_s: float = 60.0) -> bool:
        return self._drained_event.wait(timeout_s)

    def _preemption_loop(self) -> None:
        """The PR-10 doom sources, serving flavor: poll the chaos
        ``preemption`` seam (the TPU maintenance-event analog the
        training-side PreemptionWatcher also consumes) and drain on a
        notice.  Zero cost with no plan armed."""
        poll_s = env_float("SERVING_PREEMPT_POLL_S", 0.2)
        from horovod_tpu import chaos
        while not self._stop.wait(poll_s):
            if self.batcher.draining:
                return
            try:
                applied = chaos.fire("preemption")
            except Exception:
                continue
            if any(kind == "notice" for _seam, kind in applied):
                self.drain(source="preemption")
                return

    # -- readiness / health -------------------------------------------------
    def ready_doc(self) -> dict:
        depth = self.batcher.queue_depth()
        ready = (self._model_loaded and not self.batcher.draining
                 and depth <= self._ready_queue_max)
        return {"ready": ready, "replica": self.replica_id,
                "model_loaded": self._model_loaded,
                "draining": self.batcher.draining,
                "queue_depth": depth,
                "queue_budget": self._ready_queue_max,
                "version": self._version,
                "pinned": self._pin}

    def health_doc(self) -> dict:
        return {"status": "ok" if self._loop_alive else "starting",
                "replica": self.replica_id,
                "draining": self.batcher.draining,
                "drained": self.drained(),
                "version": self._version}

    # -- request path -------------------------------------------------------
    def handle_infer(self, doc: dict, trace=None) -> tuple:
        """(HTTP code, response doc).  Runs on a handler thread.
        ``trace`` is the dispatching router's attempt span (decoded
        from the ``traceparent`` header): this replica's ``serve`` span
        — covering batcher queue wait and the padded forward — becomes
        its child, so a hedged request's tree covers BOTH replicas."""
        from horovod_tpu import chaos
        from horovod_tpu import tracing
        req_id = str(doc.get("id") or f"anon-{time.monotonic_ns()}")
        serve_ctx = tracing.child(trace, "serving")
        t_handle = time.monotonic()
        wall_handle = time.time()
        # chaos seam: `error` RAISES inside fire() -> caught here as
        # 500 (the router must retry it to a survivor); `shed` is a
        # pure-signal kind -> explicit 429; `delay` sleeps in place
        # (the router's hedge must cover it)
        try:
            applied = chaos.fire("serving.request")
        except Exception as e:
            return 500, {"id": req_id, "error": f"chaos: {e!r}"}
        kinds = {kind for _seam, kind in applied}
        if "shed" in kinds:
            smetrics.inc_shed("chaos")
            return 429, {"id": req_id, "error": "chaos: injected shed"}
        # idempotency: an already-answered id returns the SAME response
        cached = self._cached_response(req_id)
        if cached is not None:
            tracing.record_span(
                "serving", "serve", serve_ctx, start=wall_handle,
                dur_s=time.monotonic() - t_handle,
                replica=self.replica_id, cached=True)
            return 200, cached
        try:
            x = np.asarray(doc.get("x"), dtype=np.float32)
        except (TypeError, ValueError):
            return 400, {"id": req_id, "error": "bad 'x' payload"}
        if x.shape != (self.dim,):
            # reject at admission: a wrong-width payload co-batched
            # with healthy requests would fail the WHOLE batch
            return 400, {"id": req_id,
                         "error": f"bad 'x' shape {x.shape}; this "
                                  f"replica serves width {self.dim}"}
        deadline_ms = doc.get("deadline_ms")
        deadline_s = float(deadline_ms) / 1000.0 \
            if deadline_ms is not None else None
        # in-flight dedup: a hedged duplicate joins the live request
        with self._pending_lock:
            pending = self._pending.get(req_id)
            fresh = pending is None
            if fresh:
                try:
                    pending = self.batcher.submit(req_id, x,
                                                  deadline_s=deadline_s)
                except DrainingError:
                    smetrics.inc_shed("draining")
                    return 503, {"id": req_id, "error": "draining"}
                except SheddedError as e:
                    return 429, {"id": req_id, "error": str(e)}
                self._pending[req_id] = pending
        try:
            wait_s = (pending.deadline - time.monotonic()) + 1.0
            y, version = pending.wait(timeout=max(wait_s, 0.1))
            resp = {"id": req_id, "y": np.asarray(y).tolist(),
                    "version": version, "replica": self.replica_id}
            if serve_ctx is not None:
                # the response names its trace so clients/benches can
                # join it against the span store without headers
                resp["trace"] = serve_ctx.trace_id
                resp["span"] = serve_ctx.span_id
            # the request's path THROUGH this replica: queue wait
            # (enqueue → batch formation) and the padded forward, as
            # child spans of the serve span — the per-hop latency
            # attribution the `diagnostics trace` tree prints
            queue_s = max(pending.formed_at - pending.enqueued_at, 0.0) \
                if pending.formed_at else 0.0
            # the replica slice of the request ledger
            # (docs/OBSERVABILITY.md "Serving request ledger"): the
            # four stages sum EXACTLY to this handler's wall time, so
            # the router can close the books — batch_wait is formation
            # → forward launch minus the named swap pause, response is
            # everything else (pre-queue admission, wakeup, assembly)
            total_s = time.monotonic() - t_handle
            swap_s = max(pending.swap_pause_s, 0.0)
            batch_wait_s = max(
                pending.started_at - pending.formed_at - swap_s, 0.0) \
                if pending.started_at and pending.formed_at else 0.0
            stages = {
                "queue": queue_s,
                "batch_wait": batch_wait_s,
                "forward": max(pending.forward_s, 0.0),
                "response": max(total_s - queue_s - batch_wait_s
                                - swap_s - pending.forward_s, 0.0),
            }
            if swap_s > 0:
                stages["swap_pause"] = swap_s
            resp["stages"] = {k: round(v, 6)
                              for k, v in stages.items()}
            tracing.record_span(
                "serving", "batcher_queue",
                tracing.child(serve_ctx, "serving"),
                start=wall_handle, dur_s=queue_s,
                replica=self.replica_id)
            tracing.record_span(
                "serving", "padded_forward",
                tracing.child(serve_ctx, "serving"),
                start=wall_handle + queue_s, dur_s=pending.forward_s,
                replica=self.replica_id, version=version)
            tracing.record_span(
                "serving", "serve", serve_ctx, start=wall_handle,
                dur_s=total_s,
                replica=self.replica_id, version=version,
                queue_s=round(queue_s, 6),
                forward_s=round(pending.forward_s, 6),
                **{f"stage_{k}": round(v, 6)
                   for k, v in stages.items() if v > 0})
            if fresh:
                # cache BEFORE the finally pops the in-flight entry: a
                # duplicate arriving in between must hit one of the two
                # (pop-then-cache would open a window where it
                # recomputes — possibly against freshly-swapped weights
                # — and returns a DIFFERENT answer)
                self._cache_response(req_id, resp)
            return 200, resp
        except DeadlineError as e:
            return 504, {"id": req_id, "error": str(e)}
        except Exception as e:
            return 500, {"id": req_id, "error": repr(e)}
        finally:
            if fresh:
                with self._pending_lock:
                    self._pending.pop(req_id, None)

    def handle_generate(self, doc: dict, trace=None) -> tuple:
        """(HTTP code, response doc) for ``POST /generate``: admit the
        prompt into the continuous-batching engine and block until the
        sequence finishes (tokens ride back in one response; streaming
        consumers use the engine API directly).

        Idempotency is the hedge-dedupe contract for MULTI-TOKEN
        responses: a duplicate of an id that is still decoding joins
        the live request BEFORE any second decode could start (the
        ``_pending`` table is checked under the same lock the fresh
        submit fills it), and a duplicate of a finished id replays the
        cached response — one id never decodes twice on this replica.
        Cross-replica duplication is closed on the router side: it
        never hedges /generate dispatches."""
        from horovod_tpu import chaos
        from horovod_tpu import tracing
        if self.engine is None:
            return 404, {"error": "this replica does not serve "
                                  "generate (mode=infer)"}
        req_id = str(doc.get("id") or f"anon-{time.monotonic_ns()}")
        serve_ctx = tracing.child(trace, "serving")
        t_handle = time.monotonic()
        wall_handle = time.time()
        try:
            applied = chaos.fire("serving.request")
        except Exception as e:
            return 500, {"id": req_id, "error": f"chaos: {e!r}"}
        if "shed" in {kind for _seam, kind in applied}:
            smetrics.inc_shed("chaos")
            return 429, {"id": req_id, "error": "chaos: injected shed"}
        cached = self._cached_response(req_id)
        if cached is not None:
            tracing.record_span(
                "serving", "serve", serve_ctx, start=wall_handle,
                dur_s=time.monotonic() - t_handle,
                replica=self.replica_id, mode="generate", cached=True)
            return 200, cached
        try:
            prompt = np.asarray(doc.get("prompt"),
                                dtype=np.int32).reshape(-1)
        except (TypeError, ValueError):
            return 400, {"id": req_id, "error": "bad 'prompt' payload"}
        try:
            max_new = int(doc.get("max_new") or 16)
        except (TypeError, ValueError):
            return 400, {"id": req_id, "error": "bad 'max_new'"}
        deadline_ms = doc.get("deadline_ms")
        deadline_s = float(deadline_ms) / 1000.0 \
            if deadline_ms is not None else None
        with self._pending_lock:
            pending = self._pending.get(req_id)
            fresh = pending is None
            if fresh:
                try:
                    req = self.engine.submit(req_id, prompt, max_new,
                                             deadline_s=deadline_s,
                                             trace=serve_ctx)
                except DrainingError:
                    smetrics.inc_shed("draining")
                    return 503, {"id": req_id, "error": "draining"}
                except SheddedError as e:
                    return 429, {"id": req_id, "error": str(e)}
                except ValueError as e:
                    # definitive client error (too long, bad max_new):
                    # the router must NOT retry it fleet-wide
                    return 400, {"id": req_id, "error": str(e)}
                pending = req.pending
                self._pending[req_id] = pending
        try:
            wait_s = (pending.deadline - time.monotonic()) + 1.0
            result = pending.wait(timeout=max(wait_s, 0.1))
            resp = {"id": req_id, **result,
                    "version": self._version,
                    "replica": self.replica_id}
            if serve_ctx is not None:
                resp["trace"] = serve_ctx.trace_id
                resp["span"] = serve_ctx.span_id
            # merge the engine's ledger slice with the handler's:
            # ``response`` is the handler wall-clock OUTSIDE the
            # engine's submit→finish interval; the engine's own host
            # bookkeeping between ticks stays in the router's
            # unattributed residual — never relabeled
            total_s = time.monotonic() - t_handle
            stages = {k: float(v)
                      for k, v in (result.get("stages") or {}).items()}
            stages["response"] = max(
                total_s - float(result.get("total_s") or 0.0), 0.0)
            resp["stages"] = {k: round(v, 6)
                              for k, v in stages.items()}
            tracing.record_span(
                "serving", "serve", serve_ctx, start=wall_handle,
                dur_s=total_s,
                replica=self.replica_id, mode="generate",
                tokens_emitted=result.get("tokens_emitted"),
                finish_reason=result.get("finish_reason"),
                **{f"stage_{k}": round(v, 6)
                   for k, v in stages.items() if v > 0})
            if fresh:
                # cache BEFORE the finally pops the in-flight entry
                # (same window as handle_infer: a duplicate arriving in
                # between must hit one of the two, never re-decode)
                self._cache_response(req_id, resp)
            return 200, resp
        except DeadlineError as e:
            return 504, {"id": req_id, "error": str(e)}
        except Exception as e:
            return 500, {"id": req_id, "error": repr(e)}
        finally:
            if fresh:
                with self._pending_lock:
                    self._pending.pop(req_id, None)

    def _cached_response(self, req_id: str) -> Optional[dict]:
        with self._pending_lock:
            resp = self._resp_cache.get(req_id)
            if resp is not None:
                self._resp_cache.move_to_end(req_id)
                smetrics._reg().counter(
                    "hvd_serving_duplicate_hits_total",
                    help="hedged/retried duplicates answered from the "
                         "idempotent response cache").inc()
            return resp

    def _cache_response(self, req_id: str, resp: dict) -> None:
        with self._pending_lock:
            self._resp_cache[req_id] = resp
            while len(self._resp_cache) > self._resp_cache_max:
                self._resp_cache.popitem(last=False)

    # -- the serving loop ---------------------------------------------------
    def _serve_loop(self) -> None:
        self._loop_alive = True
        while not self._stop.is_set():
            if self.batcher.draining and self.batcher.drained():
                # stay alive to answer /healthz while the embedder
                # decides to exit; nothing left to serve
                time.sleep(0.05)
                continue
            batch = self.batcher.next_batch(timeout_s=0.2)
            if not batch:
                continue
            try:
                self._run_batch(batch)
            except Exception as e:
                for req in batch:
                    req.set_error(e)
            finally:
                self.batcher.batch_done()

    def _run_batch(self, batch) -> None:
        # params-lock acquire time IS the weight-swap pause this batch
        # sat out (the hot swap holds the lock only for the pointer
        # flip) — named in the request ledger instead of hiding in
        # batch_wait
        t_lock = time.monotonic()
        with self._params_lock:
            params, version = self._params, self._version
        swap_pause_s = time.monotonic() - t_lock
        n = len(batch)
        xs = [np.atleast_1d(r.payload) for r in batch]
        width = xs[0].shape[-1]
        # pad to the FIXED max batch: one compiled forward per width
        padded = np.zeros((self.batcher.max_batch_size, width),
                          dtype=np.float32)
        for i, x in enumerate(xs):
            padded[i, :] = x
        t0 = time.monotonic()
        for req in batch:
            req.started_at = t0
            req.swap_pause_s = swap_pause_s
        out = np.asarray(self._compiled(params, padded))
        forward_s = time.monotonic() - t0
        smetrics.observe_batch(n, top=self.batcher.max_batch_size)
        smetrics._reg().histogram(
            "hvd_serving_forward_seconds",
            help="compiled forward-pass wall time per batch",
            buckets=smetrics.LATENCY_BUCKETS).observe(forward_s)
        for i, req in enumerate(batch):
            # the version rides the result: a response must name the
            # weights that COMPUTED it, not whatever is live by the
            # time the handler unblocks (a swap can land in between)
            req.forward_s = forward_s
            req.set_result((out[i], version))


# -- HTTP front ---------------------------------------------------------------
class _ReplicaHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # silence access lines
        pass

    def _send(self, code: int, doc: dict,
              ctype: str = "application/json") -> None:
        try:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except OSError:
            pass  # client vanished; accounting happened upstream

    def do_GET(self):
        replica: ReplicaServer = self.server.replica
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/readyz":
            doc = replica.ready_doc()
            self._send(200 if doc["ready"] else 503, doc)
        elif path == "/healthz":
            doc = replica.health_doc()
            self._send(200 if doc["status"] == "ok" else 503, doc)
        elif path == "/metrics":
            from horovod_tpu.metrics.registry import (default_registry,
                                                      render_prometheus)
            body = render_prometheus(default_registry().snapshot())
            try:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except OSError:
                pass
        elif path == "/status":
            self._send(200, {"ready": replica.ready_doc(),
                             "health": replica.health_doc()})
        else:
            self._send(404, {"error": "not found"})

    def do_POST(self):
        replica: ReplicaServer = self.server.replica
        path = self.path.split("?", 1)[0].rstrip("/")
        if path in ("/infer", "/generate"):
            try:
                length = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(length))
            except (ValueError, OSError):
                self._send(400, {"error": "bad request body"})
                return
            from horovod_tpu import tracing
            trace = tracing.decode(self.headers.get(tracing.TRACEPARENT))
            handler = replica.handle_generate if path == "/generate" \
                else replica.handle_infer
            code, resp = handler(doc, trace=trace)
            self._send(code, resp)
        elif path == "/drain":
            replica.drain(source="admin")
            self._send(200, {"draining": True,
                             "replica": replica.replica_id})
        elif path == "/pin":
            # {"version": N, "reason": "pin"|"rollback"} pins; a null/
            # absent version unpins.  The rollout controller's control
            # seam — same flip as a hot swap, never a dropped request.
            try:
                length = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(length)) if length \
                    else {}
            except (ValueError, OSError):
                self._send(400, {"error": "bad request body"})
                return
            version = doc.get("version")
            reason = str(doc.get("reason") or "pin")
            try:
                if version is None:
                    self._send(200, replica.unpin())
                else:
                    self._send(200, replica.pin(int(version),
                                                reason=reason))
            except Exception as e:
                self._send(500, {"error": repr(e),
                                 "replica": replica.replica_id})
        else:
            self._send(404, {"error": "not found"})


# -- subprocess entry ---------------------------------------------------------
def main(argv=None) -> int:
    """``python -m horovod_tpu.serving.replica`` — one fleet-managed
    replica process.  Prints ``SERVING port=<p> version=<v>`` once
    ready; exits 0 after a drain completes (the fleet classifies exit
    code 0 as DRAINED — planned, never failure evidence)."""
    p = argparse.ArgumentParser(prog="horovod_tpu.serving.replica")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--store-dir", default=None)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--replica-id", default="r0")
    p.add_argument("--mode", choices=("infer", "generate"),
                   default="infer",
                   help="generate adds the continuous-batching decode "
                        "engine (POST /generate, demo transformer)")
    p.add_argument("--pin-version", type=int, default=None,
                   help="restore and HOLD this durable-store step "
                        "instead of chasing the latest commit (fleet "
                        "heals during a rollout spawn pinned)")
    args = p.parse_args(argv)

    # the chaos plan (preemption notices, serving.request faults) arms
    # from env exactly like a training worker; the fleet exports
    # HVD_TPU_RANK=<slot> so rank-scoped rules can target ONE replica
    from horovod_tpu import chaos
    chaos.install()
    # crash hooks: an uncaught exception — or, with
    # HVD_TPU_FLIGHT_DUMP_ON_EXIT=1, any exit — leaves this replica's
    # flight ring (serve/queue/forward trace spans included) as a dump
    # the merged timeline reader joins with the router's
    from horovod_tpu.diagnostics.flight_recorder import \
        install_crash_hooks
    install_crash_hooks()

    replica = ReplicaServer(store_dir=args.store_dir, dim=args.dim,
                            port=args.port,
                            replica_id=args.replica_id,
                            mode=args.mode,
                            pin_version=args.pin_version).start()

    import signal

    def _sigterm(_sig, _frm):
        threading.Thread(target=replica.drain,
                         kwargs={"source": "sigterm"},
                         daemon=True).start()

    signal.signal(signal.SIGTERM, _sigterm)
    print(f"SERVING port={replica.port} version={replica._version}",
          flush=True)
    while not replica.wait_drained(timeout_s=1.0):
        pass
    print(f"DRAINED replica={args.replica_id} "
          f"source={replica._drain_source}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
