"""Continuous dynamic batcher: the admission edge of a serving replica.

Requests are admitted into a BOUNDED queue (backpressure is explicit:
an admission past the bound raises :class:`SheddedError`, which the
HTTP layer answers as 429 — never a silent drop), then formed into
batches by the serving loop: a batch closes when it reaches
``max_batch_size`` or the OLDEST member has waited ``max_wait_s``
(latency-bounded batching: an idle replica answers a lone request at
~zero batching delay, a busy one amortizes the forward pass).

Every request carries an absolute deadline; a request whose deadline
expires while still queued is failed at batch-formation time with
:class:`DeadlineError` (again explicit — counted as
``hvd_serving_shed_total{where="deadline"}``) instead of wasting the
accelerator on an answer nobody is waiting for.

Draining (docs/SERVING.md "Drain semantics"): :meth:`drain` atomically
stops admission (new submits raise :class:`DrainingError` → 503, so
routers stop sending) while everything already admitted is still
served; :meth:`drained` turns true once the queue is empty AND no batch
is in flight — the point at which a doomed replica may exit DRAINED.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.serving import metrics as smetrics


class SheddedError(RuntimeError):
    """Admission refused: the bounded queue is at budget (429)."""


class DrainingError(RuntimeError):
    """Admission refused: this replica is draining (503)."""


class DeadlineError(RuntimeError):
    """The request's deadline expired before compute."""


class PendingRequest:
    """One admitted request: the handler thread blocks on
    :meth:`wait`; the serving loop fulfills it with :meth:`set_result`
    / :meth:`set_error`."""

    __slots__ = ("id", "payload", "deadline", "enqueued_at",
                 "formed_at", "started_at", "forward_s",
                 "swap_pause_s", "_event", "_result", "_error")

    def __init__(self, req_id: str, payload: Any,
                 deadline: float) -> None:
        self.id = req_id
        self.payload = payload
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        # request-ledger attribution (docs/OBSERVABILITY.md "Serving
        # request ledger"): when the batch formed (queue wait ends),
        # when its forward launched (batch_wait ends — padding + params
        # lock), how long the padded forward took, and any weight-swap
        # pause the batch sat out — stamped by next_batch / the serving
        # loop
        self.formed_at: float = 0.0
        self.started_at: float = 0.0
        self.forward_s: float = 0.0
        self.swap_pause_s: float = 0.0
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None

    def set_result(self, result: Any) -> None:
        self._result = result
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise DeadlineError(f"request {self.id}: no result within "
                                f"{timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class DynamicBatcher:
    """Bounded-queue continuous batcher (knobs: docs/KNOBS.md —
    ``HVD_TPU_SERVING_MAX_BATCH``, ``_MAX_WAIT_MS``, ``_QUEUE``,
    ``_DEADLINE_MS``)."""

    def __init__(self, max_batch_size: Optional[int] = None,
                 max_wait_s: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 default_deadline_s: Optional[float] = None) -> None:
        self.max_batch_size = max_batch_size if max_batch_size \
            else env_int("SERVING_MAX_BATCH", 8)
        self.max_wait_s = max_wait_s if max_wait_s is not None \
            else env_float("SERVING_MAX_WAIT_MS", 5.0) / 1000.0
        self.max_queue = max_queue if max_queue \
            else env_int("SERVING_QUEUE", 64)
        self.default_deadline_s = default_deadline_s \
            if default_deadline_s is not None \
            else env_float("SERVING_DEADLINE_MS", 30_000.0) / 1000.0
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._draining = False
        self._inflight_batches = 0

    # -- admission ----------------------------------------------------------
    def submit(self, req_id: str, payload: Any,
               deadline_s: Optional[float] = None) -> PendingRequest:
        deadline = time.monotonic() + (
            deadline_s if deadline_s is not None
            else self.default_deadline_s)
        req = PendingRequest(req_id, payload, deadline)
        with self._not_empty:
            if self._draining:
                raise DrainingError("replica is draining")
            if len(self._q) >= self.max_queue:
                smetrics.inc_shed("queue")
                raise SheddedError(
                    f"batch queue at budget ({self.max_queue})")
            self._q.append(req)
            smetrics.set_queue_depth(len(self._q))
            self._not_empty.notify()
        return req

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    # -- batch formation ----------------------------------------------------
    def next_batch(self, timeout_s: float = 0.5) \
            -> Optional[List[PendingRequest]]:
        """The serving loop's pull: block up to ``timeout_s`` for a
        first request, then hold the batch open until it is full or the
        oldest member has waited ``max_wait_s``.  Expired-deadline
        requests are failed here and never returned.  ``None`` on
        timeout (lets the loop poll drain/swap state)."""
        deadline = time.monotonic() + timeout_s
        with self._not_empty:
            while not self._q:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._not_empty.wait(remaining)
            # batch window: open from the OLDEST member's enqueue
            window_end = self._q[0].enqueued_at + self.max_wait_s
            while len(self._q) < self.max_batch_size:
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                self._not_empty.wait(remaining)
            batch: List[PendingRequest] = []
            now = time.monotonic()
            while self._q and len(batch) < self.max_batch_size:
                req = self._q.popleft()
                if req.deadline <= now:
                    smetrics.inc_shed("deadline")
                    req.set_error(DeadlineError(
                        f"request {req.id}: deadline expired after "
                        f"{now - req.enqueued_at:.3f}s in queue"))
                    continue
                req.formed_at = now
                batch.append(req)
            smetrics.set_queue_depth(len(self._q))
            if not batch:
                return None
            self._inflight_batches += 1
            return batch

    def batch_done(self) -> None:
        """The serving loop finished (fulfilled) a batch it took."""
        with self._not_empty:
            self._inflight_batches = max(0, self._inflight_batches - 1)
            self._not_empty.notify_all()

    # -- drain --------------------------------------------------------------
    def drain(self) -> None:
        with self._not_empty:
            self._draining = True
            self._not_empty.notify_all()
        smetrics.set_draining(True)

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        """True once draining AND nothing admitted remains unanswered."""
        with self._lock:
            return self._draining and not self._q \
                and self._inflight_batches == 0

    def wait_drained(self, timeout_s: float = 30.0) -> bool:
        end = time.monotonic() + timeout_s
        with self._not_empty:
            while not (self._draining and not self._q
                       and self._inflight_batches == 0):
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._not_empty.wait(min(remaining, 0.1))
            return True
