"""``horovod_tpu.keras.elastic`` — the reference's
``horovod.tensorflow.keras.elastic`` / ``horovod.keras.elastic`` surface
(``horovod/tensorflow/keras/elastic.py``): the run decorator, the Keras
state, and the fit-loop elastic callbacks."""

from horovod_tpu.elastic import run  # noqa: F401
from horovod_tpu.tensorflow.elastic import (  # noqa: F401
    CommitStateCallback, TensorFlowKerasState, UpdateBatchStateCallback,
    UpdateEpochStateCallback)

KerasState = TensorFlowKerasState  # reference alias
