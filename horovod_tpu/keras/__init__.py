"""``horovod_tpu.keras`` — Keras-integrated callbacks and optimizer wrapper.

Reference: ``horovod/keras`` + ``horovod/_keras/callbacks.py``
(``BroadcastGlobalVariablesCallback`` :23, ``MetricAverageCallback`` :49,
``LearningRateWarmupCallback`` :118). The framework-neutral logic lives in
:mod:`horovod_tpu.train.callbacks`; these classes plug it into
``model.fit``.
"""

from __future__ import annotations

from typing import Optional

from horovod_tpu.common.basics import rank, size  # noqa: F401
from horovod_tpu.tensorflow import (  # noqa: F401
    allreduce, allgather, broadcast, broadcast_variables, init, shutdown)
from horovod_tpu.train import callbacks as _cb


def DistributedOptimizer(optimizer, op=None, compression=None,
                         backward_passes_per_step: int = 1):
    """Keras-compatible wrapper: a dynamic SUBCLASS of the given optimizer's
    class whose ``apply_gradients`` syncs gradients first (reference:
    ``horovod/_keras/__init__.py create_distributed_optimizer`` — same
    dynamic-subclass trick, required because ``model.compile`` validates
    the optimizer's type)."""
    from horovod_tpu.ops.reduce_op import Average
    from horovod_tpu.train.compression import Compression
    from horovod_tpu.tensorflow import _DistributedOptimizer

    sync = _DistributedOptimizer(optimizer, op or Average,
                                 compression or Compression.none,
                                 backward_passes_per_step)
    cls = optimizer.__class__

    class _KerasDistributed(cls):
        _hvd_sync = None

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            # sync (+ accumulation when backward_passes_per_step > 1, incl.
            # the tf.function/graph path) lives in the TF helper; its _opt
            # shim applies via THIS instance's base class so keras variable
            # state stays consistent
            return self._hvd_sync.apply_gradients(
                list(grads_and_vars), *args, **kwargs)

    _KerasDistributed.__name__ = "Distributed" + cls.__name__
    dist = _KerasDistributed.from_config(optimizer.get_config())
    dist._hvd_sync = sync

    class _SuperApply:
        """Routes the helper's final apply to the base-class method of the
        keras-registered instance (not the detached original optimizer);
        other attribute access falls through to that instance so the
        helper's __getattr__ proxy contract keeps working."""

        def apply_gradients(self, gv, *args, **kwargs):
            return cls.apply_gradients(dist, list(gv), *args, **kwargs)

        def __getattr__(self, item):
            return getattr(dist, item)

    sync._opt = _SuperApply()
    return dist


def _keras():
    import tensorflow as tf
    return tf.keras


class BroadcastGlobalVariablesCallback:
    """Broadcast model+optimizer variables from root at train begin
    (reference: ``_keras/callbacks.py:23-47``)."""

    def __new__(cls, root_rank: int = 0):
        keras = _keras()

        class _Impl(keras.callbacks.Callback):
            def __init__(self, root):
                super().__init__()
                self._root = root
                self._done = False

            def on_batch_begin(self, batch, logs=None):
                if self._done:
                    return
                broadcast_variables(self.model.variables, self._root)
                if getattr(self.model, "optimizer", None) is not None and \
                        hasattr(self.model.optimizer, "variables"):
                    vars = self.model.optimizer.variables
                    vars = vars() if callable(vars) else vars
                    broadcast_variables(vars, self._root)
                self._done = True

        return _Impl(root_rank)


class MetricAverageCallback:
    """Average epoch metrics across workers (reference:
    ``_keras/callbacks.py:49-93``)."""

    def __new__(cls):
        keras = _keras()
        impl = _cb.MetricAverageCallback()

        class _Impl(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if logs:
                    logs.update(impl.on_epoch_end(logs))

        return _Impl()


class LearningRateWarmupCallback:
    """LR warmup from base lr to lr*size (reference:
    ``_keras/callbacks.py:118-192``)."""

    def __new__(cls, initial_lr: float, warmup_epochs: int = 5,
                steps_per_epoch: Optional[int] = None, verbose: int = 0):
        keras = _keras()
        sched = _cb.LearningRateWarmupCallback(
            initial_lr, warmup_epochs, steps_per_epoch or 1).schedule()

        class _Impl(keras.callbacks.Callback):
            def __init__(self):
                super().__init__()
                self._step = 0

            def on_train_batch_begin(self, batch, logs=None):
                lr = float(sched(self._step))
                self._step += 1
                opt = self.model.optimizer
                if hasattr(opt, "learning_rate"):
                    try:
                        opt.learning_rate.assign(lr)
                    except AttributeError:
                        opt.learning_rate = lr

        return _Impl()


callbacks = type("callbacks", (), {
    "BroadcastGlobalVariablesCallback": BroadcastGlobalVariablesCallback,
    "MetricAverageCallback": MetricAverageCallback,
    "LearningRateWarmupCallback": LearningRateWarmupCallback,
})
