"""``horovod_tpu.keras`` — Keras-integrated callbacks and optimizer wrapper.

Reference: ``horovod/keras`` + ``horovod/_keras/callbacks.py``
(``BroadcastGlobalVariablesCallback`` :23, ``MetricAverageCallback`` :49,
``LearningRateWarmupCallback`` :118). The framework-neutral logic lives in
:mod:`horovod_tpu.train.callbacks`; these classes plug it into
``model.fit``.
"""

from __future__ import annotations

from typing import Optional

from horovod_tpu.common.basics import (  # noqa: F401
    local_rank, local_size, mpi_threads_supported, rank, size)
from horovod_tpu.tensorflow import (  # noqa: F401
    allreduce, allgather, broadcast, broadcast_global_variables,
    broadcast_variables, init, shutdown)
from horovod_tpu.train import callbacks as _cb


def DistributedOptimizer(optimizer, op=None, compression=None,
                         backward_passes_per_step: int = 1):
    """Keras-compatible wrapper: a dynamic SUBCLASS of the given optimizer's
    class whose ``apply_gradients`` syncs gradients first (reference:
    ``horovod/_keras/__init__.py create_distributed_optimizer`` — same
    dynamic-subclass trick, required because ``model.compile`` validates
    the optimizer's type). A fresh instance is built from the config; to
    distribute an already-built optimizer while keeping its slot state
    (the load_model path), see ``_wrap_in_place``."""
    dist = optimizer.__class__.from_config(optimizer.get_config())
    return _wrap_in_place(dist, op, compression, backward_passes_per_step)


def _keras():
    import tensorflow as tf
    return tf.keras


class BroadcastGlobalVariablesCallback:
    """Broadcast model+optimizer variables from root at train begin
    (reference: ``_keras/callbacks.py:23-47``)."""

    def __new__(cls, root_rank: int = 0):
        keras = _keras()

        class _Impl(keras.callbacks.Callback):
            def __init__(self, root):
                super().__init__()
                self._root = root
                self._done = False

            def on_batch_begin(self, batch, logs=None):
                if self._done:
                    return
                broadcast_variables(self.model.variables, self._root)
                if getattr(self.model, "optimizer", None) is not None and \
                        hasattr(self.model.optimizer, "variables"):
                    vars = self.model.optimizer.variables
                    vars = vars() if callable(vars) else vars
                    broadcast_variables(vars, self._root)
                self._done = True

        return _Impl(root_rank)


class MetricAverageCallback:
    """Average epoch metrics across workers (reference:
    ``_keras/callbacks.py:49-93``)."""

    def __new__(cls):
        keras = _keras()
        impl = _cb.MetricAverageCallback()

        class _Impl(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if logs:
                    logs.update(impl.on_epoch_end(logs))

        return _Impl()


class LearningRateWarmupCallback:
    """LR warmup from base lr to lr*size (reference:
    ``_keras/callbacks.py:118-192``)."""

    def __new__(cls, initial_lr: float, warmup_epochs: int = 5,
                steps_per_epoch: Optional[int] = None, verbose: int = 0):
        keras = _keras()
        sched = _cb.LearningRateWarmupCallback(
            initial_lr, warmup_epochs, steps_per_epoch or 1).schedule()

        class _Impl(keras.callbacks.Callback):
            def __init__(self):
                super().__init__()
                self._step = 0

            def on_train_batch_begin(self, batch, logs=None):
                lr = float(sched(self._step))
                self._step += 1
                opt = self.model.optimizer
                if hasattr(opt, "learning_rate"):
                    try:
                        opt.learning_rate.assign(lr)
                    except AttributeError:
                        opt.learning_rate = lr

        return _Impl()


callbacks = type("callbacks", (), {
    "BroadcastGlobalVariablesCallback": BroadcastGlobalVariablesCallback,
    "MetricAverageCallback": MetricAverageCallback,
    "LearningRateWarmupCallback": LearningRateWarmupCallback,
})


def _wrap_in_place(optimizer, op=None, compression=None,
                   backward_passes_per_step: int = 1):
    """Make an optimizer instance distributed by swapping in a dynamic
    subclass WITHOUT re-instantiating, so built variables and restored
    slot state (momentum, Adam moments, ...) stay live. Shared engine of
    DistributedOptimizer (which feeds it a fresh from_config instance)
    and load_model (which feeds it the checkpoint-loaded one)."""
    from horovod_tpu.ops.reduce_op import Average
    from horovod_tpu.train.compression import Compression
    from horovod_tpu.tensorflow import _DistributedOptimizer

    cls = optimizer.__class__

    class _KerasDistributed(cls):
        _hvd_sync = None

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            # sync (+ accumulation when backward_passes_per_step > 1, incl.
            # the tf.function/graph path) lives in the TF helper; its _opt
            # shim applies via THIS instance's base class so keras variable
            # state stays consistent
            return self._hvd_sync.apply_gradients(
                list(grads_and_vars), *args, **kwargs)

    _KerasDistributed.__name__ = "Distributed" + cls.__name__
    optimizer.__class__ = _KerasDistributed
    sync = _DistributedOptimizer(optimizer, op or Average,
                                 compression or Compression.none,
                                 backward_passes_per_step)

    class _SuperApply:
        """Routes the helper's final apply to the base-class method of the
        keras-registered instance; other attribute access falls through so
        the helper's __getattr__ proxy contract keeps working."""

        def apply_gradients(self, gv, *args, **kwargs):
            return cls.apply_gradients(optimizer, list(gv), *args, **kwargs)

        def __getattr__(self, item):
            return getattr(optimizer, item)

    sync._opt = _SuperApply()
    optimizer._hvd_sync = sync
    return optimizer


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Load a saved keras model and make its optimizer distributed so the
    restored model keeps training across workers (reference:
    ``horovod/keras/__init__.py:167`` — there via load-time custom-object
    substitution of every optimizer class; here the loaded instance's
    class is swapped for the distributed subclass in place, which keeps
    its checkpointed slot state). ``custom_optimizers`` are extra
    optimizer classes needed for deserialization; they merge into
    ``custom_objects``."""
    keras = _keras()
    co = dict(custom_objects or {})
    for c in (custom_optimizers or []):
        co.setdefault(c.__name__, c)
    model = keras.models.load_model(filepath, custom_objects=co)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        _wrap_in_place(opt, compression=compression)
    return model


def __getattr__(name):
    if name == "elastic":
        # lazy (the elastic submodule pulls the TF adapter); import_module
        # directly — a from-import here would recurse through this very
        # __getattr__ via importlib's fromlist handling
        import importlib
        mod = importlib.import_module("horovod_tpu.keras.elastic")
        globals()["elastic"] = mod
        return mod
    raise AttributeError(name)
