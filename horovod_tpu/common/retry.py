"""Unified transient-error retry: jittered exponential backoff under a
total-deadline budget, with per-call-site metrics.

Before this module every network-ish call site hand-rolled its own shield
(``runner/http_kv.py`` had a fixed 4-attempt loop with no jitter and no
cap on total wall time; ``diagnostics/autopsy.py`` peer fetches were
single-attempt; ``runner/tpu_discovery.py`` probed once), so behavior
under the exact faults the chaos harness injects (docs/CHAOS.md) differed
per call site.  One policy engine gives every adopter:

* **exponential backoff with jitter** — synchronized retries from a whole
  pod hammering a just-restarted KV server is a thundering herd; jitter
  de-correlates them;
* **a total-deadline budget** — callers state their intent ("this lookup
  is worth ~10s"), and retrying stops when the budget is spent rather
  than after an attempt count whose wall time nobody computed;
* **per-call-site metrics** — ``hvd_retry_attempts_total{site=...}``
  (transient errors absorbed) and ``hvd_retry_exhausted_total{site=...}``
  (gave up), so /metrics shows WHICH plane is flaky before it becomes an
  outage.

Reference analog: none — the reference hand-rolls retries per call site
too (e.g. ``horovod/runner/http/http_client.py``); SURVEY.md flags the
lack of a shared policy.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from horovod_tpu.common.safe_metrics import safe_inc as _metric

# module-level singleton RNG for jitter; deterministic tests inject their
# own via the rng= parameter
_RNG = random.Random()


def retry_call(fn: Callable,
               site: str,
               retry_on: Tuple[Type[BaseException], ...] = (OSError,),
               give_up_on: Tuple[Type[BaseException], ...] = (),
               attempts: int = 4,
               base_delay_s: float = 0.05,
               backoff: float = 2.0,
               max_delay_s: float = 2.0,
               jitter: float = 0.25,
               deadline_s: Optional[float] = None,
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               clock: Callable[[], float] = time.monotonic,
               count_exhausted: bool = True):
    """Call ``fn()``; on a transient error, back off and try again.

    Args:
      fn: zero-arg callable; its return value is returned on success.
      site: stable call-site label for the retry/exhaustion metrics and
        log records (e.g. ``"http_kv"``, ``"autopsy.peer_fetch"``).
      retry_on: exception types considered transient.
      give_up_on: exception types re-raised immediately even when they
        subclass a ``retry_on`` type (e.g. ``urllib.error.HTTPError`` is
        an ``OSError`` but a 404 will not heal with patience).
      attempts: maximum total attempts (first call included).
      base_delay_s / backoff / max_delay_s: delay before retry *i* is
        ``min(max_delay_s, base_delay_s * backoff**i)`` pre-jitter.
      jitter: fractional jitter; each sleep is scaled by a uniform factor
        in ``[1 - jitter, 1 + jitter]``.
      deadline_s: total wall-time budget across attempts AND sleeps; when
        the budget cannot fit the next sleep, retrying stops and the last
        error is raised (counted as exhaustion).  ``None`` = attempts
        alone bound the loop.
      sleep / rng / clock: injectable for tests.
      count_exhausted: when False, exhaustion skips the
        ``hvd_retry_exhausted_total`` tick (the attempts metric and the
        log still land).  For callers whose exhaustion is an EXPECTED
        outcome of a declared condition — a worker polling through a
        driver-takeover window under ``HVD_TPU_DRIVER_OUTAGE_GRACE_S``
        (docs/ELASTIC.md "Driver failover & takeover") — where the alarm
        metric would be a false positive on every planned takeover.

    Raises: the last transient error on exhaustion; non-retryable errors
    immediately.
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    if attempts == 1:
        # no retry policy in effect — a plain call.  Skipping the
        # metrics/log keeps single-attempt probes (running_on_tpu_vm off
        # TPU) from raising false "retry exhausted" alarms on /metrics.
        return fn()
    r = rng or _RNG
    start = clock()
    for attempt in range(attempts):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            _metric("hvd_retry_attempts_total",
                    "transient errors absorbed by retry_call, per site",
                    site=site)
            last_chance = attempt == attempts - 1
            delay = min(max_delay_s, base_delay_s * backoff ** attempt)
            delay *= 1.0 + jitter * (2.0 * r.random() - 1.0)
            over_budget = (deadline_s is not None and
                           clock() - start + delay > deadline_s)
            if last_chance or over_budget:
                if count_exhausted:
                    _metric("hvd_retry_exhausted_total",
                            "retry_call gave up (attempts or deadline "
                            "spent), per site", site=site)
                _log_exhausted(site, attempt + 1, clock() - start, e)
                raise
            sleep(max(delay, 0.0))
    raise AssertionError("unreachable")  # pragma: no cover


def _log_exhausted(site: str, tried: int, elapsed: float,
                   err: BaseException) -> None:
    try:
        from horovod_tpu.common.logging import get_logger
        get_logger().warning(
            "retry[%s]: giving up after %d attempt(s) over %.2fs: %r",
            site, tried, elapsed, err)
    except Exception:
        pass
