"""Environment-variable configuration surface.

Mirrors the reference's env-knob config system (reference:
``horovod/common/common.h:107-139`` knob list, parsed in
``horovod/common/operations.cc:487-588`` and ``horovod/common/utils/env_parser.cc``).
Every knob accepts a ``HOROVOD_``-prefixed name for drop-in familiarity and an
``HVD_TPU_``-prefixed alias; the ``HVD_TPU_`` name wins if both are set.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env(name: str, default: Optional[str] = None) -> Optional[str]:
    """Read ``HVD_TPU_<name>`` falling back to ``HOROVOD_<name>``."""
    v = os.environ.get("HVD_TPU_" + name)
    if v is None:
        v = os.environ.get("HOROVOD_" + name)
    return default if v is None else v


def env_int(name: str, default: int) -> int:
    v = _env(name)
    try:
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    v = _env(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def env_bool(name: str, default: bool = False) -> bool:
    v = _env(name)
    if v in (None, ""):
        return default
    return v.lower() not in ("0", "false", "no", "off")


def env_str(name: str, default: str = "") -> str:
    v = _env(name)
    return default if v in (None, "") else v


@dataclasses.dataclass
class Config:
    """Snapshot of all runtime knobs.

    Defaults follow the reference: fusion threshold 64 MiB — the
    reference's own default (``operations.cc:487``) and what our C++
    core's env parser falls back to (``capi.cc``); the two layers must
    agree because the bucket planner (``train/buckets.py``) reuses this
    number as the overlap bucket budget. Cycle time 1 ms; cache
    capacity 1024.
    """

    # Fusion / cycle (reference: operations.cc:487-538)
    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    # Gradient bucketing / overlap (docs/PERF.md "Overlap & bucketing"):
    # bucket_bytes 0 = follow fusion_threshold_bytes; overlap_buckets
    # gates the eager per-bucket async issue path (off = one grouped
    # call for the whole tree, the pre-bucketing behavior).
    bucket_bytes: int = 0
    overlap_buckets: bool = True
    # Small-bucket latency floor (docs/PERF.md "Autotuning"): gradient
    # buckets under this many bytes skip quantization and ring /
    # hierarchical chunking and take one dense psum (latency-optimized
    # small-tensor path, arxiv 1909.09756). 0 = off.
    small_bucket_floor: int = 0
    # Mesh-path communication autotuner (train/autotune.py): online plan
    # search over bucket_bytes x algorithm x codec x small-bucket floor
    # on the traced path, bounded by a step budget, winner persisted to
    # a fingerprint-keyed JSON cache. Distinct from the C++ core's
    # eager-path autotune= below.
    autotune_mesh: bool = False
    autotune_budget_steps: int = 48
    autotune_cache_dir: str = ""
    # Hierarchical ops (reference: operations.cc:514-538)
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    # Autotune (reference: parameter_manager.h:42-105)
    autotune: bool = False
    autotune_log: str = ""
    autotune_warmup_samples: int = 3
    autotune_steps_per_sample: int = 10
    autotune_bayes_opt_max_samples: int = 24
    autotune_gaussian_process_noise: float = 1e-6
    # Timeline (reference: timeline.h:48-183)
    timeline: str = ""
    timeline_mark_cycles: bool = False
    # Diagnostics (docs/OBSERVABILITY.md "Flight recorder & hang
    # autopsy"): every rank writes a timeline shard
    # (<timeline>.rank<r>.json) with span ids + wall-clock anchors;
    # merge with `python -m horovod_tpu.diagnostics merge`.  The other
    # diagnostics knobs (WATCHDOG_SECONDS, FLIGHT_RECORDER_SIZE,
    # AUTOPSY_DIR) are read live from env by horovod_tpu/diagnostics —
    # they must track env changes across elastic re-init and tests, so
    # they deliberately bypass this cached snapshot.
    timeline_all_ranks: bool = False
    # Stall inspection (reference: stall_inspector.h:30-99)
    stall_check_disable: bool = False
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    # Elastic
    elastic: bool = False
    reset_limit: int = 0
    # Backend selection (reference: HOROVOD_CPU_OPERATIONS / HOROVOD_CONTROLLER,
    # common.h:128; here XLA is the TPU data plane, TCP the host reference plane)
    tpu_operations: str = "XLA"
    controller: str = "tcp"
    # Group fusion (reference: HOROVOD_DISABLE_GROUP_FUSION, group_table.h)
    disable_group_fusion: bool = False
    # Compression
    compression_fp16_on_tpu: bool = True
    # Transport (reference: HOROVOD_GLOO_TIMEOUT_SECONDS)
    gloo_timeout_seconds: float = 30.0
    # Background-thread CPU pinning (reference: HOROVOD_THREAD_AFFINITY)
    thread_affinity: int = -1
    # Metrics / telemetry (docs/OBSERVABILITY.md)
    # Per-worker Prometheus exporter base port; 0 = disabled. Worker i on a
    # host binds metrics_port + local_rank(i).
    metrics_port: int = 0
    # Coordinator logs a rank-attributed negotiation-wait summary every
    # this many seconds; 0 = disabled (snapshot stays queryable via
    # hvd.metrics_snapshot() either way).
    straggler_report_secs: float = 0.0
    # Misc
    log_level: str = "WARNING"
    log_hide_timestamp: bool = False
    rendezvous_addr: str = ""
    rendezvous_port: int = 0

    @classmethod
    def from_env(cls) -> "Config":
        d = cls()
        return cls(
            fusion_threshold_bytes=env_int(
                "FUSION_THRESHOLD", d.fusion_threshold_bytes),
            cycle_time_ms=env_float("CYCLE_TIME", d.cycle_time_ms),
            bucket_bytes=env_int("BUCKET_BYTES", d.bucket_bytes),
            overlap_buckets=env_bool("OVERLAP_BUCKETS", d.overlap_buckets),
            small_bucket_floor=env_int("SMALL_BUCKET_FLOOR",
                                       d.small_bucket_floor),
            autotune_mesh=env_bool("AUTOTUNE_MESH"),
            autotune_budget_steps=env_int("AUTOTUNE_BUDGET_STEPS",
                                          d.autotune_budget_steps),
            autotune_cache_dir=env_str("AUTOTUNE_CACHE_DIR",
                                       d.autotune_cache_dir),
            cache_capacity=env_int("CACHE_CAPACITY", d.cache_capacity),
            hierarchical_allreduce=env_bool("HIERARCHICAL_ALLREDUCE"),
            hierarchical_allgather=env_bool("HIERARCHICAL_ALLGATHER"),
            autotune=env_bool("AUTOTUNE"),
            autotune_log=env_str("AUTOTUNE_LOG"),
            autotune_warmup_samples=env_int(
                "AUTOTUNE_WARMUP_SAMPLES", d.autotune_warmup_samples),
            autotune_steps_per_sample=env_int(
                "AUTOTUNE_STEPS_PER_SAMPLE", d.autotune_steps_per_sample),
            autotune_bayes_opt_max_samples=env_int(
                "AUTOTUNE_BAYES_OPT_MAX_SAMPLES",
                d.autotune_bayes_opt_max_samples),
            autotune_gaussian_process_noise=env_float(
                "AUTOTUNE_GAUSSIAN_PROCESS_NOISE",
                d.autotune_gaussian_process_noise),
            timeline=env_str("TIMELINE"),
            timeline_mark_cycles=env_bool("TIMELINE_MARK_CYCLES"),
            timeline_all_ranks=env_bool("TIMELINE_ALL_RANKS"),
            stall_check_disable=env_bool("STALL_CHECK_DISABLE"),
            stall_warning_time_seconds=env_float(
                "STALL_CHECK_TIME_SECONDS", d.stall_warning_time_seconds),
            stall_shutdown_time_seconds=env_float(
                "STALL_SHUTDOWN_TIME_SECONDS", d.stall_shutdown_time_seconds),
            elastic=env_bool("ELASTIC"),
            reset_limit=env_int("RESET_LIMIT", d.reset_limit),
            tpu_operations=env_str("TPU_OPERATIONS", d.tpu_operations).upper(),
            controller=env_str("CONTROLLER", d.controller).lower(),
            disable_group_fusion=env_bool("DISABLE_GROUP_FUSION"),
            compression_fp16_on_tpu=env_bool(
                "COMPRESSION_FP16_ON_TPU", d.compression_fp16_on_tpu),
            gloo_timeout_seconds=env_float("GLOO_TIMEOUT_SECONDS",
                                           d.gloo_timeout_seconds),
            metrics_port=env_int("METRICS_PORT", d.metrics_port),
            straggler_report_secs=env_float(
                "STRAGGLER_REPORT_SECONDS", d.straggler_report_secs),
            thread_affinity=env_int("THREAD_AFFINITY", d.thread_affinity),
            log_level=env_str("LOG_LEVEL", d.log_level).upper(),
            log_hide_timestamp=env_bool("LOG_HIDE_TIME",
                                        d.log_hide_timestamp),
            rendezvous_addr=env_str("RENDEZVOUS_ADDR",
                                    os.environ.get("HOROVOD_GLOO_RENDEZVOUS_ADDR", "")),
            rendezvous_port=env_int("RENDEZVOUS_PORT", d.rendezvous_port),
        )


_config: Optional[Config] = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config.from_env()
    return _config


def reset_config() -> None:
    """Re-read env on next access (used by elastic re-init and tests)."""
    global _config
    _config = None
