"""Chrome-tracing timeline for the host control plane.

Reference: ``horovod/common/timeline.{h,cc}`` — a lock-free SPSC queue feeding
a dedicated writer thread, producing chrome://tracing JSON; activity names in
``horovod/common/common.h:73-105``; dynamic start/stop via the C API
(``operations.cc:1011-1041``). TPU equivalent: the same host-side negotiation
timeline, while device-side profiling is delegated to ``jax.profiler``
(see :func:`horovod_tpu.utils.profiler.trace`).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import List, Optional

# Reference activity names (common.h:73-105 subset relevant on TPU).
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
NEGOTIATE_ALLTOALL = "NEGOTIATE_ALLTOALL"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
WAIT_FOR_OTHER_TENSOR_DATA = "WAIT_FOR_OTHER_TENSOR_DATA"
QUEUE = "QUEUE"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
COMPUTE = "COMPUTE"
XLA_COLLECTIVE = "XLA_COLLECTIVE"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"


def shard_path(base: str, rank: int) -> str:
    """Per-rank shard path for timeline base ``base``
    (``HVD_TPU_TIMELINE``): ``<dir>/timeline.rank<r>.json`` when base is
    a directory, else ``<base>.rank<r>.json`` next to the rank-0 file —
    distinct from the path the C++ core owns on rank 0, so the two
    writers never interleave."""
    if base.endswith(os.sep) or os.path.isdir(base):
        return os.path.join(base, f"timeline.rank{rank}.json")
    return f"{base}.rank{rank}.json"


def shard_paths_for(base: str) -> List[str]:
    """Existing shard files for ``base`` (merger/autopsy discovery)."""
    if base.endswith(os.sep) or os.path.isdir(base):
        from horovod_tpu.diagnostics.merge import find_shards
        return find_shards(base)
    import glob
    return sorted(glob.glob(f"{base}.rank*.json"))


class Timeline:
    """Asynchronous chrome-tracing writer.

    Events are enqueued from hot paths and serialized by a writer thread
    (mirrors the reference's SPSC-queue + writer-thread design,
    ``timeline.h:84-86``). Only the coordinator (rank 0) writes a file by
    default, matching ``operations.cc:459-475``.
    """

    def __init__(self, rank: int, file_path: str = "") -> None:
        self._rank = rank
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._file = None
        self._started = False
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._mark_cycles = False
        if file_path:
            self.start(file_path)

    # -- lifecycle ---------------------------------------------------------
    def start(self, file_path: str, mark_cycles: bool = False,
              force: bool = False, meta: Optional[dict] = None) -> None:
        """``force=True`` opens a file on ANY rank (per-rank shard mode,
        ``HVD_TPU_TIMELINE_ALL_RANKS``); ``meta`` args are embedded as
        the shard's leading ``SHARD_META`` event with a wall-clock
        anchor so the merger can align shards across hosts."""
        with self._lock:
            if self._started:
                return
            self._mark_cycles = mark_cycles
            if self._rank != 0 and not force:
                # Workers keep timeline state but only rank 0 writes a file
                # (reference: coordinator-only file, operations.cc:459-475).
                self._started = True
                return
            try:
                self._file = open(file_path, "w")
            except OSError:
                return
            # fresh queue per generation: a writer thread that outlived a
            # timed-out stop() keeps its OLD queue/file and can never
            # steal (or corrupt) this generation's events
            self._q = queue.Queue()
            self._file.write("[\n")
            if meta is not None:
                # wall + monotonic sampled back-to-back: the merger maps
                # event ts onto the wall clock via this anchor pair
                wall, mono = time.time(), time.monotonic()
                self._file.write(json.dumps({
                    "ph": "i", "name": "SHARD_META", "pid": self._rank,
                    "tid": "meta", "ts": (mono - self._t0) * 1e6,
                    "s": "g",
                    "args": {"epoch_us": wall * 1e6, **meta},
                }) + ",\n")
            self._thread = threading.Thread(
                target=self._writer_loop, args=(self._q, self._file),
                name="hvd-tpu-timeline", daemon=True)
            self._thread.start()
            self._started = True

    def stop(self) -> None:
        # Phase 1 (under the lock): flip _started so no new emission can
        # begin, and detach the writer thread handle. The join happens
        # OUTSIDE the lock — _emit now serializes on the same lock, and a
        # join while holding it would deadlock an emitter waiting to bail.
        with self._lock:
            if not self._started:
                return
            self._started = False
            thread, self._thread = self._thread, None
        if thread is not None:
            self._q.put(None)
            thread.join(timeout=5)
        # Phase 2: drain stragglers that slipped in before _started
        # flipped — the old stop/emit race dropped those events silently
        # with the file already closed. Only safe once the writer has
        # actually exited: draining concurrently with a writer that
        # outlived the join would interleave writes into the same file
        # and could swallow its shutdown sentinel.
        with self._lock:
            if self._file is not None:
                try:
                    if thread is None or not thread.is_alive():
                        while True:
                            try:
                                ev = self._q.get_nowait()
                            except queue.Empty:
                                break
                            if ev is not None:
                                self._file.write(json.dumps(ev) + ",\n")
                        self._file.write("{}]\n")
                    self._file.close()
                except (OSError, ValueError):
                    pass
                self._file = None

    def start_shard(self, path: str, wall_offset_s: float = 0.0,
                    mark_cycles: bool = False) -> None:
        """Open a per-rank shard at ``path`` (any rank) with merge
        metadata: this rank, ``source=host`` and the estimated wall
        offset to the coordinator (:mod:`horovod_tpu.diagnostics.clock`)."""
        self.start(path, mark_cycles=mark_cycles, force=True,
                   meta={"rank": self._rank, "source": "host",
                         "wall_offset_us": wall_offset_s * 1e6})

    def flush(self, timeout: float = 1.0) -> None:
        """Best-effort: let the writer drain so an autopsy reading the
        shard file mid-run sees the recent events (the writer flushes
        per event; truncated tails are repaired by the merger)."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def close(self) -> None:
        self.stop()

    @property
    def enabled(self) -> bool:
        return self._started

    # -- event emission ----------------------------------------------------
    def _emit(self, ph: str, name: str, cat: str, tid: str,
              args: Optional[dict] = None) -> None:
        # cheap unguarded pre-check keeps the disabled path lock-free...
        if not self._started or self._file is None:
            return
        ev = {"ph": ph, "name": name, "cat": cat, "pid": self._rank,
              "tid": tid, "ts": (time.monotonic() - self._t0) * 1e6}
        if args:
            ev["args"] = args
        # ...but enqueueing re-checks under the lock: stop() flips
        # _started under the same lock before draining, so an event that
        # makes it into the queue here is guaranteed to be written (either
        # by the writer thread or by stop()'s drain), never dropped into a
        # closed file.
        with self._lock:
            if not self._started or self._file is None:
                return
            self._q.put(ev)

    def activity_start(self, tensor_name: str, activity: str) -> None:
        self._emit("B", activity, "activity", tensor_name)

    def activity_end(self, tensor_name: str) -> None:
        self._emit("E", "", "activity", tensor_name)

    def negotiate_start(self, tensor_name: str, op_name: str) -> None:
        self._emit("B", f"NEGOTIATE_{op_name.upper()}", "negotiate", tensor_name)

    def negotiate_end(self, tensor_name: str) -> None:
        self._emit("E", "", "negotiate", tensor_name)

    # Per-collective spans (diagnostics cross-rank trace): B/E on the
    # tensor-name track, carrying the span id every rank computes
    # identically (horovod_tpu.diagnostics.spans) so the merger can
    # correlate the same collective across rank tracks.
    def collective_begin(self, tensor_name: str, kind: str,
                         span: str) -> None:
        self._emit("B", kind.upper(), "collective", tensor_name,
                   {"span": span})

    def collective_end(self, tensor_name: str, span: str,
                       ok: bool = True) -> None:
        args = {"span": span}
        if not ok:
            args["error"] = True
        self._emit("E", "", "collective", tensor_name, args)

    def mark_cycle(self) -> None:
        """Cycle tick marker (reference: HOROVOD_TIMELINE_MARK_CYCLES)."""
        if self._mark_cycles:
            self._emit("i", "CYCLE_START", "cycle", "cycle")

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._emit("i", name, "marker", "marker", args)

    # -- writer thread -----------------------------------------------------
    def _writer_loop(self, q: "queue.Queue[Optional[dict]]", file) -> None:
        # q/file are bound at thread start: a writer leaked past stop()'s
        # join timeout must keep writing ITS generation, never a new one
        while True:
            ev = q.get()
            if ev is None:
                return
            try:
                file.write(json.dumps(ev) + ",\n")
                # flush on drain, not per event (same policy as the C++
                # writer): batches syscalls when a high-rate trace backs
                # the queue up, while an idle — or hung — shard still
                # has a fresh tail on disk for the autopsy
                if q.empty():
                    file.flush()
            except (OSError, ValueError):
                return
