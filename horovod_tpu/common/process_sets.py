"""Process sets: named sub-groups of ranks with their own collectives.

Reference: ``horovod/common/process_set.h:26-168``, ``process_set.cc``, Python
user API ``horovod/common/process_sets.py:18-160``, dynamic registration
``horovod/common/operations.cc:1194-1260``.

TPU-native design: a process set owns (a) a sub-backend for eager host
collectives over its ranks and (b) a slice of the data-plane mesh so that
jitted collectives can run over the corresponding devices (building block for
MoE / model-parallel hybrids, as in the reference).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence


class ProcessSet:
    """User-facing handle (reference: ``process_sets.py:18-70``)."""

    process_set_id: Optional[int]
    ranks: Optional[List[int]]

    def __init__(self, ranks: Optional[Sequence[int]] = None) -> None:
        self.process_set_id = None
        self.ranks = sorted(set(ranks)) if ranks is not None else None

    def included(self) -> bool:
        from horovod_tpu.common.basics import rank
        if self.ranks is None:
            return True
        return rank() in self.ranks

    def rank(self) -> int:
        """Rank of this process within the set (-1 if excluded)."""
        from horovod_tpu.common.basics import rank as global_rank
        if self.ranks is None:
            return global_rank()
        try:
            return self.ranks.index(global_rank())
        except ValueError:
            return -1

    def size(self) -> int:
        from horovod_tpu.common.basics import size as global_size
        if self.ranks is None:
            return global_size()
        return len(self.ranks)

    def __eq__(self, other) -> bool:
        return (isinstance(other, ProcessSet)
                and self.process_set_id == other.process_set_id
                and self.ranks == other.ranks)

    def __hash__(self) -> int:
        return hash((self.process_set_id, tuple(self.ranks or ())))

    def __repr__(self) -> str:
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


#: The global process set containing every rank (id 0, like the reference's
#: global ProcessSet at table slot 0 — ``process_set.h:86-168``).
global_process_set = ProcessSet()
global_process_set.process_set_id = 0


class _ProcessSetTable:
    """Registry (reference: ``ProcessSetTable``, ``process_set.h:86-168``)."""

    def __init__(self, state) -> None:
        self._state = state
        self._lock = threading.Lock()
        self._next_id = 1
        self._sets: Dict[int, ProcessSet] = {0: global_process_set}
        self._backends: Dict[int, object] = {0: state.backend}

    def register(self, ps: ProcessSet) -> int:
        with self._lock:
            if ps.ranks is None:
                ps.ranks = list(range(self._state.size))
            for existing in self._sets.values():
                e_ranks = existing.ranks if existing.ranks is not None \
                    else list(range(self._state.size))
                if e_ranks == ps.ranks:
                    ps.process_set_id = existing.process_set_id
                    return ps.process_set_id
            psid = self._next_id
            self._next_id += 1
            ps.process_set_id = psid
            self._sets[psid] = ps
            self._backends[psid] = self._state.backend.make_subset(ps.ranks)
            return psid

    def remove(self, ps: ProcessSet) -> None:
        with self._lock:
            if ps.process_set_id in (None, 0):
                raise ValueError(
                    "Cannot remove an unregistered or the global process set.")
            be = self._backends.pop(ps.process_set_id, None)
            self._sets.pop(ps.process_set_id, None)
            if be is not None and be is not self._state.backend:
                be.shutdown()
            ps.process_set_id = None

    def backend_for(self, ps: ProcessSet):
        with self._lock:
            if ps.process_set_id is None or ps.process_set_id not in self._sets:
                raise ValueError(f"Unknown process set: {ps!r}")
            return self._backends[ps.process_set_id]

    def ids(self) -> List[int]:
        with self._lock:
            return sorted(self._sets)

    def get(self, psid: int) -> ProcessSet:
        with self._lock:
            return self._sets[psid]


def _init_process_set_table(state, initial_sets: List[ProcessSet]):
    global_process_set.ranks = list(range(state.size))
    table = _ProcessSetTable(state)
    for ps in initial_sets:
        table.register(ps)
    return table


def _table() -> _ProcessSetTable:
    from horovod_tpu.common.basics import _require_init
    return _require_init().process_set_table


def add_process_set(process_set) -> ProcessSet:
    """Register a new process set (reference: ``add_process_set``,
    ``process_sets.py:100-130`` → ``horovod_add_process_set``,
    ``operations.cc:1194-1229``)."""
    if not isinstance(process_set, ProcessSet):
        process_set = ProcessSet(process_set)
    _table().register(process_set)
    return process_set


def remove_process_set(process_set: ProcessSet) -> None:
    """Reference: ``remove_process_set`` (``process_sets.py:133-152``)."""
    _table().remove(process_set)


def process_set_ids() -> List[int]:
    return _table().ids()


def get_process_set_by_id(psid: int) -> ProcessSet:
    return _table().get(psid)
