"""Leveled, rank-tagged logging (reference: ``horovod/common/logging.{h,cc}``,
``LOG(level, rank)`` macros)."""

from __future__ import annotations

import logging
import os
import sys

_LOGGER = None


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        from horovod_tpu.common.config import get_config
        logger = logging.getLogger("horovod_tpu")
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            rank = os.environ.get("HOROVOD_RANK", os.environ.get("HVD_TPU_RANK", "?"))
            h.setFormatter(logging.Formatter(
                f"[%(asctime)s] [hvd-tpu] [rank {rank}] %(levelname)s: %(message)s"))
            logger.addHandler(h)
        level = getattr(logging, get_config().log_level, logging.WARNING)
        logger.setLevel(level)
        _LOGGER = logger
    return _LOGGER
