"""Leveled, rank-tagged logging (reference: ``horovod/common/logging.{h,cc}``,
``LOG(level, rank)`` macros)."""

from __future__ import annotations

import logging
import os
import sys

_LOGGER = None


class _RankFilter(logging.Filter):
    """Resolve the rank (and active collective span) lazily, per record.

    The logger is frequently touched before the launcher's env setup (any
    import-time ``get_logger()`` call), and the old read-once-at-creation
    scheme then stamped ``[rank ?]`` on every later line. Per-record
    resolution follows the config precedence (``HVD_TPU_`` beats
    ``HOROVOD_``) and picks up the identity whenever it appears.

    ``record.span`` carries the thread's active per-collective span id
    (``horovod_tpu.diagnostics.spans``) so a log line emitted inside a
    traced collective can be joined against the merged cross-rank trace
    (the trace events carry the same id in ``args.span``); empty
    otherwise, keeping untraced lines byte-identical to before."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.rank = os.environ.get(
            "HVD_TPU_RANK", os.environ.get("HOROVOD_RANK", "?"))
        record.span = ""
        try:
            from horovod_tpu.diagnostics.spans import current_span
            span = current_span()
            if span:
                record.span = f" [span {span}]"
        except Exception:
            pass
        return True


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        from horovod_tpu.common.config import get_config
        logger = logging.getLogger("horovod_tpu")
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.addFilter(_RankFilter())
            # HOROVOD_LOG_HIDE_TIME drops the timestamp (reference knob)
            ts = "" if get_config().log_hide_timestamp else "[%(asctime)s] "
            h.setFormatter(logging.Formatter(
                f"{ts}[hvd-tpu] [rank %(rank)s]%(span)s "
                "%(levelname)s: %(message)s"))
            logger.addHandler(h)
        name = get_config().log_level
        if name == "TRACE":  # python logging has no TRACE tier
            name = "DEBUG"
        elif name == "FATAL":
            name = "CRITICAL"
        level = getattr(logging, name, logging.WARNING)
        logger.setLevel(level)
        _LOGGER = logger
    return _LOGGER


def reset_logger() -> None:
    """Drop the cached logger + handlers so the next ``get_logger()``
    re-reads level/format config (tests and elastic re-init)."""
    global _LOGGER
    logger = logging.getLogger("horovod_tpu")
    for h in list(logger.handlers):
        logger.removeHandler(h)
    _LOGGER = None
