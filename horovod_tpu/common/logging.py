"""Leveled, rank-tagged logging (reference: ``horovod/common/logging.{h,cc}``,
``LOG(level, rank)`` macros)."""

from __future__ import annotations

import logging
import os
import sys

_LOGGER = None


def get_logger() -> logging.Logger:
    global _LOGGER
    if _LOGGER is None:
        from horovod_tpu.common.config import get_config
        logger = logging.getLogger("horovod_tpu")
        if not logger.handlers:
            h = logging.StreamHandler(sys.stderr)
            rank = os.environ.get("HOROVOD_RANK", os.environ.get("HVD_TPU_RANK", "?"))
            # HOROVOD_LOG_HIDE_TIME drops the timestamp (reference knob)
            ts = "" if get_config().log_hide_timestamp else "[%(asctime)s] "
            h.setFormatter(logging.Formatter(
                f"{ts}[hvd-tpu] [rank {rank}] %(levelname)s: %(message)s"))
            logger.addHandler(h)
        name = get_config().log_level
        if name == "TRACE":  # python logging has no TRACE tier
            name = "DEBUG"
        elif name == "FATAL":
            name = "CRITICAL"
        level = getattr(logging, name, logging.WARNING)
        logger.setLevel(level)
        _LOGGER = logger
    return _LOGGER
