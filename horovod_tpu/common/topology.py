"""Mesh topology: (num_hosts × local_devices) structure for hierarchical
collectives.

TPU pods are two-level networks: chips on one host share fast intra-host
links (ICI), hosts talk over the slower inter-host fabric (DCN). A flat
allreduce pushes the full payload across the slow hop; the hierarchical
decomposition (MLPerf TPU-pod work, arxiv 1909.09756; the reference's
``HOROVOD_HIERARCHICAL_ALLREDUCE`` path in ``operations.cc:514-538``)
moves only ``1/local_size`` of the bytes inter-host:

    intra-host reduce_scatter → inter-host allreduce on the shard →
    intra-host allgather

This module derives that structure from jax device process indices and
turns it into the ``axis_index_groups`` the SPMD collectives need
(:func:`horovod_tpu.ops.mesh_collectives.phier_allreduce`).

``HVD_TPU_VIRTUAL_HOSTS`` imposes a virtual host split on a
single-process mesh — how the 8-device CPU test mesh exercises (2×4),
(4×2) and (8×1) topologies, and how a benchmark can measure the
hierarchy's reassociation cost without a pod.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence


class MeshTopology(NamedTuple):
    """Two-level structure of a mesh axis: ``num_hosts`` groups of
    ``local_size`` devices each, contiguous along the axis (device at
    axis position ``i`` lives on host ``i // local_size``)."""

    num_hosts: int
    local_size: int

    @property
    def world(self) -> int:
        return self.num_hosts * self.local_size

    @property
    def is_hierarchical(self) -> bool:
        """Both levels non-trivial — the only case where the two-hop
        decomposition beats a flat collective."""
        return self.num_hosts > 1 and self.local_size > 1

    def intra_groups(self) -> List[List[int]]:
        """``axis_index_groups`` for the intra-host hops: one group per
        host, its ``local_size`` consecutive axis positions."""
        L = self.local_size
        return [[h * L + i for i in range(L)]
                for h in range(self.num_hosts)]

    def inter_groups(self) -> List[List[int]]:
        """``axis_index_groups`` for the inter-host hop: one group per
        local position, the same local slot on every host. After the
        intra-host reduce_scatter, every member of group ``l`` holds
        shard ``l`` of its host's sum — reducing across the group
        completes the global reduction for that shard."""
        L = self.local_size
        return [[h * L + l for h in range(self.num_hosts)]
                for l in range(L)]


def flat_topology(n: int) -> MeshTopology:
    """The degenerate 1×n topology (no hierarchy)."""
    return MeshTopology(1, max(1, int(n)))


def virtual_hosts() -> int:
    """``HVD_TPU_VIRTUAL_HOSTS`` — impose this many virtual hosts on the
    axis (0 = derive from real process indices). Read live, not from the
    cached Config snapshot, so tests can sweep topologies."""
    from horovod_tpu.common.config import env_int
    return env_int("VIRTUAL_HOSTS", 0)


def _axis_devices(mesh, axis_name: str) -> Sequence:
    """Devices along one mesh axis (other axes pinned at coordinate 0),
    in axis order — the order ``lax.axis_index`` sees."""
    import numpy as np
    names = list(mesh.axis_names)
    ax = names.index(axis_name)
    devs = np.asarray(mesh.devices)
    index = [0] * devs.ndim
    index[ax] = slice(None)
    return list(devs[tuple(index)])


def detect_topology(mesh=None, axis_name: str = "dp",
                    n: Optional[int] = None) -> MeshTopology:
    """Derive the (num_hosts × local_devices) structure of a mesh axis.

    Precedence: ``HVD_TPU_VIRTUAL_HOSTS`` (when it evenly divides the
    axis) > jax device process indices > flat. The process-index path
    requires each host's devices to be CONTIGUOUS along the axis with
    equal counts — the layout ``jax.devices()`` and ``build_mesh``
    produce; any other arrangement degrades to flat rather than
    producing groups that cross the slow hop twice.

    ``mesh=None`` with ``n`` set derives a topology for a bare axis size
    (virtual override or flat) — what the autotuner uses when planning
    before the mesh exists.
    """
    if mesh is not None:
        devices = _axis_devices(mesh, axis_name)
        size = len(devices)
    else:
        devices = None
        size = int(n or 0)
    if size <= 1:
        return flat_topology(size or 1)

    vh = virtual_hosts()
    if vh > 0:
        if vh <= size and size % vh == 0:
            return MeshTopology(vh, size // vh)
        from horovod_tpu.common.logging import get_logger
        get_logger().warning(
            "HVD_TPU_VIRTUAL_HOSTS=%d does not evenly divide axis size "
            "%d; ignoring the virtual split", vh, size)

    if devices is None:
        return flat_topology(size)

    procs = [getattr(d, "process_index", 0) for d in devices]
    hosts = sorted(set(procs))
    if len(hosts) <= 1:
        return flat_topology(size)
    if size % len(hosts) != 0:
        return flat_topology(size)
    local = size // len(hosts)
    # contiguity + equal counts: host h owns axis slots [h*local, (h+1)*local)
    for i, p in enumerate(procs):
        if procs[(i // local) * local] != p:
            return flat_topology(size)
    return MeshTopology(len(hosts), local)
