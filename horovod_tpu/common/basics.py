"""Process identity and lifecycle: ``init`` / ``shutdown`` / rank & size queries.

TPU-native re-think of the reference's ``HorovodBasics`` ctypes wrapper
(reference: ``horovod/common/basics.py:29-487``) and the C API behind it
(``horovod/common/operations.cc:869-1083``).

Identity model on TPU: one **process per TPU host** (not per chip, unlike the
reference's one-process-per-GPU). ``rank``/``size`` count processes, as in the
reference; the chips a process drives form its local device set and are
addressed through the data-plane mesh (:mod:`horovod_tpu.parallel.mesh`). The
launcher (``hvdrun``) injects ``HOROVOD_RANK``-style env vars exactly as the
reference's launcher does (reference: ``horovod/runner/gloo_run.py:65-76``).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import List, Optional, Sequence

from horovod_tpu.common.config import Config, get_config, reset_config
from horovod_tpu.common.logging import get_logger


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        super().__init__(
            "horovod_tpu has not been initialized; call hvd.init() first.")


class _GlobalState:
    """Per-process singleton (reference: ``HorovodGlobalState``,
    ``horovod/common/global_state.h:39-126``)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.initialized = False
        self.rank = 0
        self.size = 1
        self.local_rank = 0
        self.local_size = 1
        self.cross_rank = 0
        self.cross_size = 1
        self.hostname = ""
        self.launched_rank = None  # pre-restriction rank when init(ranks) used
        self.launched_size = 1     # env world size before any restriction
        self.world_ranks = None    # restricted global set (init(ranks))
        self.backend = None          # ops.backend.Backend for the global set
        self.config: Optional[Config] = None
        self.process_set_table = None  # common.process_sets._ProcessSetTable
        self.timeline = None
        self.metrics_exporter = None  # metrics.exporter.MetricsExporter
        self.elastic_enabled = False
        self.jax_distributed_initialized = False


_state = _GlobalState()


def _read_identity_from_env() -> dict:
    """Launcher-injected identity (reference env names,
    ``horovod/runner/gloo_run.py:65-76``)."""
    def geti(name: str, default: int) -> int:
        v = os.environ.get("HVD_TPU_" + name, os.environ.get("HOROVOD_" + name))
        return int(v) if v not in (None, "") else default

    return dict(
        rank=geti("RANK", 0),
        size=geti("SIZE", 1),
        local_rank=geti("LOCAL_RANK", 0),
        local_size=geti("LOCAL_SIZE", 1),
        cross_rank=geti("CROSS_RANK", 0),
        cross_size=geti("CROSS_SIZE", 1),
        hostname=os.environ.get(
            "HVD_TPU_HOSTNAME", os.environ.get("HOROVOD_HOSTNAME", "")),
    )


def _create_backend(state: "_GlobalState"):
    """Pick the communication backend for eager (process-level) collectives.

    Priority-ordered like the reference's ``CreateOperationManager``
    (``horovod/common/operations.cc:144-253``): the first available backend
    wins. On TPU pods the data plane is XLA collectives over ICI/DCN; the
    TCP core backend is the host-side reference implementation (the
    "Gloo-equivalent") used for CPU tests and as the control plane.
    """
    from horovod_tpu.ops.backend import make_backend
    return make_backend(state)


def init(ranks: Optional[Sequence[int]] = None,
         process_sets: Optional[list] = None) -> None:
    """Initialize horovod_tpu (reference: ``horovod_init``,
    ``operations.cc:869-878`` via ``basics.py:48-146``).

    Args:
      ranks: optional restriction of the global set to a subset of launched
        processes (reference semantics of ``hvd.init(ranks)``). Rarely used.
      process_sets: optional list of :class:`~horovod_tpu.ProcessSet` to
        register at init time (reference: dynamic/static process sets,
        ``operations.cc:1194-1260``).
    """
    with _state.lock:
        if _state.initialized:
            return
        reset_config()
        _state.config = get_config()
        ident = _read_identity_from_env()
        _state.rank = ident["rank"]
        _state.size = ident["size"]
        _state.launched_size = ident["size"]
        _state.local_rank = ident["local_rank"]
        _state.local_size = ident["local_size"]
        _state.cross_rank = ident["cross_rank"]
        _state.cross_size = ident["cross_size"]
        _state.hostname = ident["hostname"] or os.uname().nodename

        # Chaos harness (docs/CHAOS.md): arm the fault plan BEFORE the
        # backend boots — transport.* rules compile into the env spec the
        # C++ core reads at Transport::Init, and rank-scoped rules must
        # track the rank an elastic re-mesh just handed us.  No plan set
        # = everything stays disarmed (zero-cost seams).
        from horovod_tpu import chaos as _chaos
        _chaos.install(rank=ident["rank"])

        if ranks is not None and len(ranks) > 0:
            ranks = sorted(set(ranks))
            # Restrict the world to the given launched ranks (reference
            # semantics of ``hvd.init(ranks)``: the global process set is the
            # sub-communicator over those ranks, and rank/size are relative
            # to it — ``operations.cc:881-965`` init_multi_comm). Launched
            # processes NOT in the list still participate in the core world
            # (so rendezvous completes) but are excluded from the global set
            # — their rank() is -1. Single-process, exclusion is an error.
            if _state.rank not in ranks:
                if _state.size == 1:
                    raise ValueError(
                        f"hvd.init(ranks={list(ranks)}): this process has "
                        f"rank {_state.rank}, which is not in the ranks "
                        "list.")
                _state.launched_rank = _state.rank
                _state.world_ranks = ranks
                _state.rank = -1
                _state.size = len(ranks)
            else:
                _state.launched_rank = _state.rank
                _state.world_ranks = ranks
                _state.rank = ranks.index(_state.rank)
                _state.size = len(ranks)

        # re-mesh timeline (docs/OBSERVABILITY.md "Re-mesh timeline"):
        # when an elastic recovery episode is active, backend creation
        # is its "rendezvous" phase and the remainder of init its
        # "rebuild" phase; both are pass-throughs on a first init
        import time as _time

        from horovod_tpu.elastic import remesh as _remesh
        with _remesh.phase("rendezvous"):
            _state.backend = _create_backend(_state)
        _t_rebuild = _time.perf_counter()

        from horovod_tpu.common.process_sets import _init_process_set_table
        _state.process_set_table = _init_process_set_table(
            _state, process_sets or [])

        # Timeline (host-side chrome tracing; reference timeline.h:48-183).
        # In multi-process mode the C++ core writes the timeline file (it
        # sees the same env var); opening it here too would interleave two
        # writers into one path — so the Python timeline only owns the file
        # single-process.  With HVD_TPU_TIMELINE_ALL_RANKS every rank
        # ALSO writes a per-rank shard (<timeline>.rank<r>.json — a
        # distinct path, never shared with the core's file) carrying
        # per-collective span ids and a wall-clock anchor, merged
        # post-hoc via `python -m horovod_tpu.diagnostics merge`.
        from horovod_tpu.common.timeline import Timeline, shard_path
        cfg = _state.config
        all_shards = bool(cfg.timeline) and cfg.timeline_all_ranks
        own_file = cfg.timeline \
            if (_state.launched_size == 1 and not all_shards) else ""
        _state.timeline = Timeline(_state.rank, own_file)
        if all_shards:
            # wall-clock offset vs the coordinator, piggybacked on the
            # just-built collective plane, so shards from skew-clocked
            # hosts align in the merged trace
            from horovod_tpu.diagnostics.clock import estimate_wall_offset
            offset = estimate_wall_offset(_state.backend)
            # the flight recorder shares the shard's offset so the
            # merged timeline (diagnostics timeline) aligns flight
            # events with shard spans across skew-clocked hosts
            from horovod_tpu.diagnostics.flight_recorder import \
                set_wall_offset
            set_wall_offset(offset)
            _state.timeline.start_shard(
                shard_path(cfg.timeline, _state.rank),
                wall_offset_s=offset,
                mark_cycles=cfg.timeline_mark_cycles)

        # Flight recorder: always on (bounded ring, docs/OBSERVABILITY.md
        # "Flight recorder & hang autopsy"); crash hooks make an uncaught
        # exception leave a dump next to the autopsy bundle.  Span
        # counters restart with the world: after an elastic re-mesh the
        # new engine counts enqueues from zero, and the Python ids must
        # keep agreeing with it.
        from horovod_tpu.diagnostics import spans as _spans
        _spans.reset()
        # observability history follows the world: the step-series
        # recorder re-reads rank + HVD_TPU_OBS_DIR (a re-mesh can
        # renumber us) and the anomaly detectors drop their baselines
        # (a different world size legitimately changes step time —
        # re-learn instead of flagging the re-mesh itself; findings
        # already flagged are kept for the autopsy)
        from horovod_tpu.metrics import timeseries as _timeseries
        _timeseries.reset()
        from horovod_tpu.metrics import anomaly as _anomaly
        _anomaly.reset_baselines()
        # the profiling detectors follow the same rule: a re-meshed
        # world legitimately recompiles its jitted steps and re-learns
        # its HBM baseline — per-function storm counts and the growth
        # detector must not accumulate across generations into false
        # recompile_storm/hbm_growth findings (the capture manager and
        # its records DO survive: cooldown + autopsy history)
        from horovod_tpu.profiling import compile_watch as _cw
        from horovod_tpu.profiling import memory as _hbm
        _cw.reset_counts()
        _hbm.reset()
        from horovod_tpu.diagnostics import watchdog as _wd
        _wd.resume()  # re-arm across an elastic shutdown->init cycle
        from horovod_tpu.diagnostics.flight_recorder import (
            install_crash_hooks, record_event)
        install_crash_hooks()
        record_event("init", rank=_state.rank, size=_state.size,
                     backend=type(_state.backend).__name__)

        _state.initialized = True

        # Per-worker /metrics + /healthz exporter (HVD_TPU_METRICS_PORT;
        # docs/OBSERVABILITY.md). After the initialized flag: /healthz
        # reports live state, and a bind failure only warns.
        from horovod_tpu.metrics.exporter import start_worker_exporter
        _state.metrics_exporter = start_worker_exporter(_state)
        # Proactive preemption watcher (docs/ELASTIC.md "Proactive drain
        # & preemption"): armed only under an elastic driver; idempotent
        # across re-meshes (the singleton reads identity from env live).
        try:
            from horovod_tpu.elastic import preemption as _preemption
            _preemption.ensure_watcher()
        except Exception:
            get_logger().debug("preemption watcher not armed",
                               exc_info=True)
        # compile observability (docs/OBSERVABILITY.md "Compile & memory
        # observability"): compile-time metrics + the recompile_storm
        # detector; idempotent, gated on HVD_TPU_COMPILE_METRICS
        try:
            from horovod_tpu.profiling import compile_watch
            compile_watch.ensure_installed()
        except Exception:
            pass
        # autopilot policy engine (docs/OBSERVABILITY.md "Autopilot"):
        # armed here so a typo'd HVD_TPU_AUTOPILOT_POLICY fails the job
        # LOUDLY at init — the same contract as a typo'd chaos fault
        # plan — instead of running policy-free; no-op when
        # HVD_TPU_AUTOPILOT=off
        from horovod_tpu import autopilot as _autopilot
        _autopilot.ensure_engine()
        _ep = _remesh.current()
        if _ep is not None and not _ep.finished:
            _ep.add_phase("rebuild", _time.perf_counter() - _t_rebuild)
        get_logger().info(
            "initialized: rank=%d size=%d local=%d/%d cross=%d/%d backend=%s",
            _state.rank, _state.size, _state.local_rank, _state.local_size,
            _state.cross_rank, _state.cross_size,
            type(_state.backend).__name__)


def shutdown(force: bool = False) -> None:
    """Tear down (reference: ``horovod_shutdown``, ``operations.cc:994-1005``).
    ``force=True`` skips the negotiated-shutdown grace — used by elastic
    in-place shrink, where a dead peer makes consensus impossible."""
    with _state.lock:
        if not _state.initialized:
            return
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event("shutdown", rank=_state.rank, force=force)
        try:
            # a watchdog must not run against a torn-down world — but an
            # elastic shutdown→init cycle must not silently disarm it
            # either, so suspend (remember armed) rather than drop;
            # init() resumes it for the new world
            from horovod_tpu.diagnostics import watchdog as _wd
            _wd.suspend()
        except Exception:
            pass
        try:
            if _state.backend is not None:
                import inspect
                params = inspect.signature(
                    _state.backend.shutdown).parameters
                if "force" in params:
                    _state.backend.shutdown(force=force)
                else:  # backends without a force knob
                    _state.backend.shutdown()
        finally:
            if _state.metrics_exporter is not None:
                try:
                    _state.metrics_exporter.stop()
                except Exception:
                    pass
                _state.metrics_exporter = None
            if _state.timeline is not None:
                _state.timeline.close()
            _state.backend = None
            _state.process_set_table = None
            _state.timeline = None
            _state.initialized = False


atexit.register(shutdown)


def is_initialized() -> bool:
    """Reference: ``horovod_is_initialized`` (``operations.cc:1007``)."""
    return _state.initialized


def _require_init() -> _GlobalState:
    if not _state.initialized:
        raise NotInitializedError()
    return _state


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Dynamic timeline start (reference: ``horovod_start_timeline``,
    ``operations.cc:1011-1041``; coordinator-only file).

    Multi-process, the C++ engine owns the timeline file (it records the
    negotiation phases and execute sub-activities); single-process the
    Python timeline does. One writer per path — never both."""
    st = _require_init()
    if st.backend is not None and st.backend.start_core_timeline(
            file_path, mark_cycles=mark_cycles):
        return
    st.timeline.start(file_path, mark_cycles=mark_cycles)


def stop_timeline() -> None:
    st = _require_init()
    if st.backend is not None and st.backend.stop_core_timeline():
        return
    st.timeline.stop()


def counters() -> dict:
    """Control-plane observability counters from the active backend:
    negotiation cycles, response-cache hits/misses/evictions, fused units,
    bytes moved. The reference exposes this only via timeline/autotune
    traces; first-class counters make the steady-state fast path
    measurable (VERDICT r2 #7). Empty dict for backends with no
    negotiating control plane (single-process / XLA-eager)."""
    st = _require_init()
    return st.backend.counters() if st.backend is not None else {}


def stragglers() -> dict:
    """Coordinator-side rank-attributed negotiation-wait report: for each
    rank, total seconds the others spent waiting on it being the LAST to
    announce a tensor, and how many tensors it held up (the C++ core's
    per-tensor negotiation tracking aggregated per rank; reference
    surfaces this only as per-tensor timeline NEGOTIATE_* spans). Only the
    coordinator (rank 0 of the core world) accumulates data; other ranks
    and non-core backends return an empty report."""
    st = _require_init()
    fn = getattr(st.backend, "stragglers", None)
    return fn() if fn is not None else {}


def engine_state() -> dict:
    """Pending-tensor autopsy snapshot from the engine
    (``hvd_engine_state_json``): per coordination domain, the tensors
    still waiting for announcements with ready/missing ranks, queue
    depth and join state.  The data behind the hang watchdog's "which
    rank is stuck in what" summary (docs/OBSERVABILITY.md "Flight
    recorder & hang autopsy").  Meaningful on the coordinator; empty for
    backends without a negotiating control plane."""
    st = _require_init()
    fn = getattr(st.backend, "engine_state", None)
    return fn() if fn is not None else {}


def metrics_snapshot() -> dict:
    """One-call observability snapshot: raw engine counters, derived
    ratios (cache-hit rate, fusion efficiency), the coordinator's
    straggler report, and the process-local metrics registry (step-time
    histograms, throughput/MFU gauges from the train-loop telemetry).
    The same data the per-worker ``/metrics`` endpoint serves, as a dict.
    """
    from horovod_tpu.metrics.engine import derived_ratios
    from horovod_tpu.metrics.registry import default_registry
    engine = counters()
    return {
        "engine": engine,
        "derived": derived_ratios(engine),
        "stragglers": stragglers(),
        "registry": default_registry().snapshot(),
    }


def rank() -> int:
    return _require_init().rank


def size() -> int:
    return _require_init().size


def local_rank() -> int:
    return _require_init().local_rank


def local_size() -> int:
    return _require_init().local_size


def cross_rank() -> int:
    return _require_init().cross_rank


def cross_size() -> int:
    return _require_init().cross_size


def is_homogeneous() -> bool:
    """True if every host runs the same number of processes
    (reference: ``horovod_is_homogeneous``, ``operations.cc:1077-1083``).

    Without a cross-host gather of local sizes (done by the controller at
    init in the multi-process core), the best local test is that this host's
    ``local_size`` times the host count accounts for every process.
    """
    st = _require_init()
    return st.local_size * max(st.cross_size, 1) == st.size


def num_devices() -> int:
    """TPU chips driven by this process (no reference analog: the reference is
    one-process-per-GPU; on TPU one process drives a host's chips)."""
    import jax
    return jax.local_device_count()


def global_device_count() -> int:
    import jax
    return jax.device_count()


# Build/availability queries (reference: horovod_mpi_built etc.,
# operations.cc:1085-1130). On TPU, XLA is the data plane; the TCP core is the
# Gloo-class host backend; there is no MPI/NCCL.
def xla_built() -> bool:
    return True


def tcp_core_built() -> bool:
    from horovod_tpu.core import core_available
    return core_available()


def gloo_built() -> bool:  # compat alias: our TCP core fills Gloo's role
    return tcp_core_built()


def mpi_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def sycl_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_enabled() -> bool:  # the TCP core is the Gloo-role plane
    return tcp_core_built()


def mpi_threads_supported() -> bool:
    return False
