"""Never-raise metric increments for control-plane paths.

Accounting must not fail the operation it counts (a KV request, a
retried call, a drain-notice publish), and the callers are light
infrastructure modules that must not pull the metrics package — whose
``__init__`` eagerly imports the whole subsystem — at import time, so
the registry import happens at the first call.
"""

from __future__ import annotations


def safe_inc(name: str, help_text: str = "", **labels) -> None:
    try:
        from horovod_tpu.metrics.registry import default_registry
        default_registry().counter(name, help=help_text,
                                   labels=labels or None).inc()
    except Exception:
        pass
