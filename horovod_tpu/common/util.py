"""Shared helpers (reference analog: ``horovod/common/util.py``)."""

from __future__ import annotations

import jax


def is_traced(tree) -> bool:
    """True if any leaf of ``tree`` is a JAX tracer (we're inside jit/grad/
    shard_map tracing, so only in-graph collectives are legal)."""
    return any(isinstance(x, jax.core.Tracer)
               for x in jax.tree_util.tree_leaves(tree))


def next_power_of_two(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()
