"""Worker-side driver-outage grace window (ride-through).

Part of "driver restart is not a job restart" (docs/ELASTIC.md "Driver
failover & takeover"): when the elastic driver crashes, every worker's
world poll and notice publish starts failing at once.  Without a
declared grace window each failure escalates the way any transport
failure does — ``hvd_retry_exhausted_total`` alarms, noisy logs, and
(past the shrink-wait deadline) workers giving up on a job whose data
plane is perfectly healthy.  The driver holds no training state; its
death should cost the fleet NOTHING but control-plane latency while the
supervisor respawns it into a journal takeover.

This module is the worker's accounting of that window:

* ``note_failure()`` on the first failed world poll opens the outage —
  flight event ``driver_outage``, gauge ``hvd_driver_outage_seconds``
  starts aging;
* ``note_success()`` on the first poll that lands again closes it —
  flight event ``driver_recovered`` with the measured outage, gauge
  back to zero, and the notification listener marked stale so the
  worker re-registers with the takeover driver (whose freshly rebound
  KV has no ``notify`` scope yet);
* ``exceeded()`` answers "has the driver been dark longer than
  ``HVD_TPU_DRIVER_OUTAGE_GRACE_S``?" — the autopsy names that finding
  ("driver dead > grace"), and it is the operator's cue that the
  supervisor is NOT coming back.

Everything here is advisory bookkeeping on the worker's poll path: it
must never raise into training, so every emission is exception-proofed.
State is process-global (one driver per worker process) and guarded by
a lock — the poll loop and the notification listener can both touch it.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


def grace_s() -> float:
    """``HVD_TPU_DRIVER_OUTAGE_GRACE_S``: how long world-poll failures
    accrue quietly before the outage counts as exceeded (default 60s —
    comfortably above a supervisor respawn + journal replay + KV rebind,
    well below any human's reaction time).  0 disables the grace
    machinery entirely: failures escalate exactly as before."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("DRIVER_OUTAGE_GRACE_S", 60.0))


def enabled() -> bool:
    return grace_s() > 0.0


_lock = threading.Lock()
_started_at: Optional[float] = None      # monotonic; None = no outage
_last_recovery: Optional[float] = None   # monotonic stamp of last heal


def note_failure() -> None:
    """A world poll (or notice publish) failed to reach the driver."""
    global _started_at
    first = False
    with _lock:
        if _started_at is None:
            _started_at = time.perf_counter()
            first = True
        age = time.perf_counter() - _started_at
    _set_gauge(age)
    if first:
        _record_flight("driver_outage", grace_s=grace_s())
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "driver unreachable: entering outage grace window "
                "(HVD_TPU_DRIVER_OUTAGE_GRACE_S=%.0fs); training "
                "continues on the cached world", grace_s())
        except Exception:
            pass


def note_success() -> None:
    """A world poll reached the driver.  Cheap no-op outside an
    outage; inside one, closes it and forces the notification listener
    to re-register (the takeover driver's KV starts with an empty
    ``notify`` scope)."""
    global _started_at, _last_recovery
    with _lock:
        if _started_at is None:
            return
        outage = time.perf_counter() - _started_at
        _started_at = None
        _last_recovery = time.perf_counter()
    _set_gauge(0.0)
    _record_flight("driver_recovered", outage_s=round(outage, 3))
    try:
        from horovod_tpu.elastic import notification
        notification.mark_stale()
    except Exception:
        pass
    try:
        from horovod_tpu.common.logging import get_logger
        get_logger().info("driver reachable again after %.1fs outage",
                          outage)
    except Exception:
        pass


def active() -> bool:
    with _lock:
        return _started_at is not None


def age_s() -> float:
    with _lock:
        if _started_at is None:
            return 0.0
        return time.perf_counter() - _started_at


def exceeded() -> bool:
    """True when the driver has been dark longer than the grace window
    — the autopsy's "driver dead > grace" finding."""
    return enabled() and age_s() > grace_s()


def last_recovery_perf() -> Optional[float]:
    """``time.perf_counter()`` stamp of the most recent recovery, or
    None.  The re-mesh timeline uses it to mark episodes that spanned a
    takeover (``history --remesh``)."""
    with _lock:
        return _last_recovery


def reset() -> None:
    """Tests: drop all outage state without emitting."""
    global _started_at, _last_recovery
    with _lock:
        _started_at = None
        _last_recovery = None


def _set_gauge(value: float) -> None:
    try:
        from horovod_tpu.metrics.registry import default_registry
        default_registry().gauge(
            "hvd_driver_outage_seconds",
            help="age of the current driver outage as seen from this "
                 "worker's world polls (0 = driver reachable)",
            agg="max").set(value)
    except Exception:
        pass


def _record_flight(kind: str, **fields) -> None:
    try:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(kind, **fields)
    except Exception:
        pass
