"""Worker-side elastic training API.

Reference: ``horovod/common/elastic.py`` (``State``/``ObjectState``
commit/restore/sync :26-148, ``run_fn`` retry loop :151-175) and
``horovod/torch/elastic/state.py`` (framework state handlers).

TPU-native model: elasticity is process-restart based (see
``runner/elastic/driver.py`` docstring) — ``State.commit()`` persists to the
driver-provided checkpoint directory so a relaunched generation resumes
where the last commit left off, and ``sync()`` broadcasts from rank 0 so
fresh workers join consistently. ``HorovodInternalError`` still triggers an
in-process ``restore()`` retry exactly as in the reference.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import numpy as np

from horovod_tpu.common.basics import is_initialized
from horovod_tpu.common.basics import rank as _hvd_rank
from horovod_tpu.common.basics import size as _hvd_size


def rank() -> int:
    """Worker rank — from hvd if initialized, else the launcher env (elastic
    states are usable with the raw core backend too)."""
    if is_initialized():
        return _hvd_rank()
    return int(os.environ.get("HOROVOD_RANK", os.environ.get("HVD_TPU_RANK",
                                                             "0")))


def size() -> int:
    if is_initialized():
        return _hvd_size()
    return int(os.environ.get("HOROVOD_SIZE", os.environ.get("HVD_TPU_SIZE",
                                                             "1")))


class HorovodInternalError(RuntimeError):
    """A collective failed mid-step (reference: ``HorovodInternalError``)."""


class HostsUpdatedInterrupt(RuntimeError):
    """Membership changed; re-sync required (reference:
    ``HostsUpdatedInterrupt``)."""


class State:
    """Commit/restore/sync contract (reference: ``common/elastic.py:26-96``)."""

    def __init__(self) -> None:
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def commit(self) -> None:
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        # Process-restart elasticity: membership changes arrive as process
        # restarts, not in-band notifications, so this is a no-op hook kept
        # for reference API parity.
        pass

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


def _ckpt_path(name: str) -> Optional[str]:
    """Generation-restart persistence path — ONLY when the elastic driver
    manages this job (it exports a per-job ``HVD_ELASTIC_CKPT``,
    ``runner/elastic/driver.py``). Without a driver there is no restart
    mechanism to resume from, and persisting to a shared tempdir would let
    a later unrelated job silently adopt stale state — so standalone
    ObjectStates stay host-memory-only, like the reference's."""
    base = os.environ.get("HVD_ELASTIC_CKPT")
    if not base:
        return None
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, f"hvd_state_{name}.pkl")


class ObjectState(State):
    """Arbitrary-attribute state with pickle persistence + rank-0 broadcast
    sync (reference: ``ObjectState``, ``common/elastic.py:99-148``)."""

    def __init__(self, name: str = "default", **kwargs: Any) -> None:
        super().__init__()
        self._name = name
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._attrs = list(kwargs)
        if not self._maybe_load():
            self._snapshot()

    def _public(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._attrs}

    def _snapshot(self) -> None:
        self._saved = {k: _copy_leaf(v) for k, v in self._public().items()}

    def _maybe_load(self) -> bool:
        path = _ckpt_path(self._name)
        if path is None or not os.path.exists(path):
            return False
        try:
            with open(path, "rb") as f:
                data = pickle.load(f)
        except Exception:
            return False
        for k, v in data.items():
            setattr(self, k, v)
            if k not in self._attrs:
                self._attrs.append(k)
        self._snapshot()
        return True

    def save(self) -> None:
        self._snapshot()
        path = _ckpt_path(self._name)
        if rank() == 0 and path is not None:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(self._saved, f)
            os.replace(tmp, path)

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, _copy_leaf(v))
        self.on_reset()

    def sync(self) -> None:
        if size() > 1:
            from horovod_tpu.train.optimizer import broadcast_object
            data = broadcast_object(self._public(), root_rank=0,
                                    name=f"elastic.{self._name}")
            for k, v in data.items():
                setattr(self, k, v)
        self._snapshot()


def _copy_leaf(v: Any) -> Any:
    try:
        import jax
        if isinstance(v, jax.Array):
            return np.asarray(v).copy()
    except ImportError:
        pass
    if isinstance(v, np.ndarray):
        return v.copy()
    if isinstance(v, (dict, list, tuple)):
        return pickle.loads(pickle.dumps(v))
    return v


class TpuState(ObjectState):
    """Convenience for (params, opt_state, ...) pytrees of jax arrays —
    the analog of ``TorchState`` (``torch/elastic/state.py:27``)."""


def run(func: Callable) -> Callable:
    """Elastic run decorator (reference: ``run_fn``,
    ``common/elastic.py:151-175``): retry on HorovodInternalError with
    ``state.restore()``; resync on HostsUpdatedInterrupt."""

    def wrapper(state: State, *args: Any, **kwargs: Any):
        state.sync()
        while True:
            try:
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                state.restore()
                state.sync()
            except HostsUpdatedInterrupt:
                state.sync()

    return wrapper
