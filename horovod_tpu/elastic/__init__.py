"""Worker-side elastic training API.

Reference: ``horovod/common/elastic.py`` (``State``/``ObjectState``
commit/restore/sync :26-148, ``run_fn`` retry loop :151-175) and
``horovod/torch/elastic/state.py`` (framework state handlers).

TPU-native model: elasticity is process-restart based (see
``runner/elastic/driver.py`` docstring) — ``State.commit()`` persists to the
driver-provided checkpoint directory so a relaunched generation resumes
where the last commit left off, and ``sync()`` broadcasts from rank 0 so
fresh workers join consistently. ``HorovodInternalError`` still triggers an
in-process ``restore()`` retry exactly as in the reference.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import numpy as np

from horovod_tpu.common.basics import is_initialized
from horovod_tpu.common.basics import rank as _hvd_rank
from horovod_tpu.common.basics import size as _hvd_size


def rank() -> int:
    """Worker rank — from hvd if initialized, else the launcher env (elastic
    states are usable with the raw core backend too)."""
    if is_initialized():
        return _hvd_rank()
    return int(os.environ.get("HOROVOD_RANK", os.environ.get("HVD_TPU_RANK",
                                                             "0")))


def size() -> int:
    if is_initialized():
        return _hvd_size()
    return int(os.environ.get("HOROVOD_SIZE", os.environ.get("HVD_TPU_SIZE",
                                                             "1")))


class HorovodInternalError(RuntimeError):
    """A collective failed mid-step (reference: ``HorovodInternalError``)."""


class HostsUpdatedInterrupt(RuntimeError):
    """Membership changed; re-sync required (reference:
    ``HostsUpdatedInterrupt``). Carries the driver's new world document
    when growth-resync is active."""

    def __init__(self, update: Optional[dict] = None) -> None:
        super().__init__("hosts updated")
        self.update = update


_current_generation: Optional[int] = None


def world_doc_signature(secret: bytes, doc: dict) -> str:
    """HMAC over the canonical world doc — workers apply env/coordinator
    changes from this document, so it must not be forgeable by anyone who
    can reach the driver's KV port."""
    import hashlib
    import hmac
    import json
    body = json.dumps({k: v for k, v in doc.items() if k != "sig"},
                      sort_keys=True).encode()
    return hmac.new(secret, body, hashlib.sha256).hexdigest()


def _validate_doc(raw: Optional[bytes]) -> Optional[dict]:
    """Parse + HMAC-verify a world doc and keep it only when its
    generation is newer than ours — shared by both delivery channels
    (a pushed doc is no more trusted than a polled one: the listener
    port is open to the network)."""
    if raw is None:
        return None
    import hmac as _hmac
    import json
    try:
        doc = json.loads(raw)
        if not isinstance(doc, dict):
            return None
        secret_hex = os.environ.get("HVD_ELASTIC_SECRET", "")
        if secret_hex:
            expect = world_doc_signature(bytes.fromhex(secret_hex), doc)
            sig = doc.get("sig", "")
            if not isinstance(sig, str) or \
                    not _hmac.compare_digest(sig, expect):
                return None  # forged/corrupt doc: ignore
        if int(doc.get("generation", 0)) > _current_generation:
            return doc
    except (ValueError, TypeError, AttributeError):
        # anyone can PUT bytes at the listener port: malformed docs must
        # never escalate past "ignored" (a crash here kills training)
        return None
    return None


def _world_update(poll: bool = True) -> Optional[dict]:
    """A newer world document, if the driver published one. Checked in
    channel order: (1) the push channel — the driver POSTs the doc to a
    per-worker listener the moment it publishes (reference:
    ``runner/elastic/worker.py:46+`` WorkerNotificationService), so this
    is one in-process read; (2) with ``poll=True``, a poll of the driver
    KV as fallback for lost pushes (the original pull-at-commit design).
    """
    global _current_generation
    kv = os.environ.get("HVD_ELASTIC_KV", "")
    if not kv:
        return None
    if _current_generation is None:
        _current_generation = int(
            os.environ.get("HVD_ELASTIC_GENERATION", "0"))
    addr, _, port = kv.rpartition(":")

    # listener setup (bind + driver registration, up to one 5s kv_put) only
    # happens on the COMMIT path; the mid-step probe (poll=False) must stay
    # an in-process read and just sees "nothing yet" before first commit
    from horovod_tpu.elastic.notification import (current_listener,
                                                  ensure_listener)
    listener = current_listener() if not poll else \
        ensure_listener(addr, int(port))
    if listener is not None:
        doc = _validate_doc(listener.pending_raw())
        if doc is not None:
            return doc
    if not poll:
        return None
    from horovod_tpu.elastic import outage
    try:
        from horovod_tpu.runner import kv_relay
        # short timeout: commit() must stay cheap even if the driver's
        # port silently drops packets.  Routed through the KV relay tree
        # when enabled (HVD_TPU_KV_RELAY_ARITY): the poll hits an
        # O(arity) parent's cache instead of the root, and degrades to a
        # direct root read when the parent is dead (docs/ELASTIC.md
        # "Relayed control-plane KV").  During a driver outage the polls
        # relabel to their own retry site and stop raising exhausted
        # alarms: a takeover window is a declared condition, not a fault
        # (docs/ELASTIC.md "Driver failover & takeover").
        raw = kv_relay.client(addr, int(port)).get(
            "world", "current", timeout=3.0,
            site="elastic.driver_outage" if outage.active()
            else "elastic.world_poll",
            count_exhausted=not outage.enabled())
    except OSError:
        # driver KV unreachable: open (or age) the outage window and
        # keep training on the cached world — ride-through, not escalate
        outage.note_failure()
        return None
    outage.note_success()
    return _validate_doc(raw)


def _publish_result() -> None:
    """Publish this worker's signed completion receipt
    (``result/<rank>``) to the driver KV.  A takeover driver that
    ADOPTED an already-running worker (docs/ELASTIC.md "Driver failover
    & takeover") never sees that worker's exit code — its original
    parent died with the old driver's process tree — so success is
    classified from this receipt instead.  HMAC-signed with the world
    secret: the receipt decides a SUCCESS classification and must not be
    forgeable by anyone who can reach the KV port.  Best-effort: a
    worker finishing while no driver is reachable just exits (the
    takeover driver's backstop classifies it conservatively)."""
    kv = os.environ.get("HVD_ELASTIC_KV", "")
    if not kv:
        return
    try:
        import json
        addr, _, port = kv.rpartition(":")
        doc = {"rank": rank(),
               "generation": int(
                   os.environ.get("HVD_ELASTIC_GENERATION", "0")),
               "ok": True}
        secret_hex = os.environ.get("HVD_ELASTIC_SECRET", "")
        if secret_hex:
            doc["sig"] = world_doc_signature(
                bytes.fromhex(secret_hex), doc)
        from horovod_tpu.runner import kv_relay
        kv_relay.client(addr, int(port)).put(
            "result", str(doc["rank"]), json.dumps(doc).encode(),
            timeout=5.0, site="elastic.result")
    except (OSError, ValueError):
        pass


def has_pending_update() -> bool:
    """True when a newer world document has already ARRIVED at this worker
    (pushed by the driver) — without any driver round-trip. A long-running
    step can check this cheaply mid-step to decide to commit early."""
    return _world_update(poll=False) is not None


def _apply_world_update(update: dict, force_shutdown: bool = False) -> None:
    """Re-initialize into the new world IN PLACE (no process restart):
    survivors look up their slot by their CURRENT rank (growth keeps
    ranks stable; shrink docs are keyed by survivors' old ranks and may
    assign a smaller new rank), adopt the new size/topology env, tear the
    old core down and rendezvous into the new world.
    ``force_shutdown=True`` skips the shutdown-consensus grace — used on
    the shrink path, where a DEAD peer makes consensus impossible (growth
    keeps the negotiated drain: every survivor reaches its next commit).
    Reference analog: ``reset()`` after HostsUpdatedInterrupt,
    ``common/elastic.py:151-175``."""
    global _current_generation
    import horovod_tpu as hvd
    from horovod_tpu.diagnostics.flight_recorder import record_event
    from horovod_tpu.elastic import remesh
    my_rank = str(rank())
    old_size = size()
    record_event("elastic_remesh", generation=update.get("generation"),
                 old_size=old_size, new_size=update.get("size"))
    slot_env = update["slots"].get(my_rank)
    if slot_env is None:  # we are not part of the new world
        hvd.shutdown(force=True)  # close our sockets for the survivors
        raise RuntimeError(
            f"rank {my_rank} is not in the new world (generation "
            f"{update['generation']}); exiting")
    # a SHRUNKEN world means departed peers: shutdown consensus cannot
    # complete, so skip its grace instead of stalling every survivor
    with remesh.phase("drain"):
        hvd.shutdown(force=force_shutdown
                     or int(update.get("size", 0)) < old_size)
    os.environ.update({k: str(v) for k, v in slot_env.items()})
    os.environ["HVD_TPU_COORD_ADDR"] = update["coord_addr"]
    os.environ["HVD_TPU_COORD_PORT"] = str(update["coord_port"])
    os.environ["HVD_ELASTIC_GENERATION"] = str(update["generation"])
    _current_generation = int(update["generation"])
    from horovod_tpu.common.config import reset_config
    reset_config()
    # hvd.init() itself splits into the "rendezvous" (backend
    # negotiation) and "rebuild" (process sets / timeline / exporter)
    # phases of the re-mesh timeline — see common/basics.py
    hvd.init()


class _NoWorldUpdateYet(Exception):
    """Internal: the driver has not published a newer world document
    (the retryable condition in :func:`_await_world_update`)."""


def _await_world_update(timeout_s: Optional[float] = None) -> Optional[dict]:
    """Poll the driver for a newer world document for up to ``timeout_s``
    (default ``HVD_ELASTIC_SHRINK_WAIT_S`` or 15s). Used after a
    HorovodInternalError: if a peer died, the driver notices its process
    exit and publishes the shrunken world within moments — the survivors
    wait here for it instead of dying for a generation restart.

    The wait rides :func:`horovod_tpu.common.retry.retry_call` (jittered
    exponential backoff under the window as a deadline budget): a whole
    pod's survivors re-polling in lockstep after a shared failure is
    exactly the thundering herd the jitter de-correlates, and the
    attempts land on ``hvd_retry_*_total{site="elastic.await_world"}``
    — exhaustion there means "no recovery world inside the window"
    (the same-world retry follows), not an outage."""
    if not os.environ.get("HVD_ELASTIC_KV"):
        # no driver manages this job: a recovery world can never arrive,
        # and waiting out the full window would stall EVERY
        # HorovodInternalError retry by 15s for nothing
        return None
    if timeout_s is None:
        timeout_s = float(os.environ.get("HVD_ELASTIC_SHRINK_WAIT_S", "15"))

    from horovod_tpu.common.retry import retry_call

    def poll():
        update = _world_update(poll=True)
        if update is None:
            raise _NoWorldUpdateYet()
        return update

    try:
        return retry_call(
            poll, site="elastic.await_world",
            retry_on=(_NoWorldUpdateYet,),
            attempts=1_000_000,  # the deadline is the real bound
            base_delay_s=0.25, backoff=1.5, max_delay_s=2.0, jitter=0.25,
            deadline_s=timeout_s)
    except _NoWorldUpdateYet:
        return None


class State:
    """Commit/restore/sync contract (reference: ``common/elastic.py:26-96``)."""

    def __init__(self) -> None:
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def commit(self) -> None:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        from horovod_tpu.elastic import remesh
        record_event("elastic_commit")
        self.save()
        # a committed unit of work after a recovery closes the re-mesh
        # timeline's first_step phase (loops without a StepTimer —
        # the raw elastic loop — still get a measured episode)
        remesh.note_step_end()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Raise :class:`HostsUpdatedInterrupt` when the driver published
        a newer world (elastic growth without restarting survivors);
        failures/shrink still arrive as process restarts."""
        update = _world_update()
        if update is not None:
            raise HostsUpdatedInterrupt(update)

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


def _ckpt_path(name: str) -> Optional[str]:
    """Generation-restart persistence path — ONLY when the elastic driver
    manages this job (it exports a per-job ``HVD_ELASTIC_CKPT``,
    ``runner/elastic/driver.py``). Without a driver there is no restart
    mechanism to resume from, and persisting to a shared tempdir would let
    a later unrelated job silently adopt stale state — so standalone
    ObjectStates stay host-memory-only, like the reference's."""
    base = os.environ.get("HVD_ELASTIC_CKPT")
    if not base:
        return None
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, f"hvd_state_{name}.pkl")


def _durable_base() -> str:
    """Directory for DURABLE (sharded, shared-filesystem) commits:
    ``CHECKPOINT_DIR`` knob first, the driver's ``HVD_ELASTIC_CKPT``
    otherwise."""
    from horovod_tpu.common.config import env_str
    return env_str("CHECKPOINT_DIR") or os.environ.get(
        "HVD_ELASTIC_CKPT") or ""


class ObjectState(State):
    """Arbitrary-attribute state with pickle persistence + rank-0 broadcast
    sync (reference: ``ObjectState``, ``common/elastic.py:99-148``).

    Persistence is two-tier: the per-host pickle is the fast local path
    (rank 0 only — it dies with the host that wrote it), and when
    durable commits are on (``durable=True`` or the
    ``HVD_TPU_ELASTIC_DURABLE`` knob) every commit ALSO lands in the
    native sharded store (:mod:`horovod_tpu.checkpoint`) under the
    shared checkpoint directory — each rank writes its shard, so the
    state survives the loss of any host and restores at a different
    world size (docs/ELASTIC.md "Durable commits")."""

    def __init__(self, name: str = "default",
                 durable: Optional[bool] = None, **kwargs: Any) -> None:
        super().__init__()
        self._name = name
        self._saved: Dict[str, Any] = {}
        self._durable_opt = durable
        self._durable_store = None
        self._durable_key = None
        self._durable_step: Optional[int] = None
        self._warned_no_durable_dir = False
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._attrs = list(kwargs)
        if not self._maybe_load():
            self._snapshot()

    def _durable(self):
        """The sharded store for durable commits, rebuilt whenever the
        world (rank/size) changes under us — an elastic re-mesh means a
        new shard partition.  ``None`` when durable commits are off."""
        from horovod_tpu.common.config import env_bool
        enabled = env_bool("ELASTIC_DURABLE", False) \
            if self._durable_opt is None else self._durable_opt
        if not enabled:
            return None
        base = _durable_base()
        if not base:
            if self._durable_opt:
                raise RuntimeError(
                    "durable elastic commits need a checkpoint directory: "
                    "set CHECKPOINT_DIR / HVD_TPU_CHECKPOINT_DIR (or run "
                    "under the elastic driver, which exports "
                    "HVD_ELASTIC_CKPT)")
            if not self._warned_no_durable_dir:
                # the env knob promised durability — failing silent would
                # be discovered only at the next host loss
                self._warned_no_durable_dir = True
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "ELASTIC_DURABLE is set but no checkpoint directory "
                    "is configured (CHECKPOINT_DIR / HVD_ELASTIC_CKPT): "
                    "commits of state %r are NOT durable", self._name)
            return None
        key = (base, rank(), size())
        if self._durable_store is not None and self._durable_key != key:
            try:
                # wait=False: a world change usually means a peer DIED —
                # the old store's in-flight commit may be waiting out the
                # full commit timeout on that peer's marker, and recovery
                # must not stall behind it (the abandoned tmp dir is
                # nonce-protected and GC'd later)
                self._durable_store.close(wait=False)
            except Exception:
                pass
            self._durable_store = None
        if self._durable_store is None:
            from horovod_tpu.checkpoint import ShardedCheckpointer
            self._durable_store = ShardedCheckpointer(
                os.path.join(base, f"hvd_state_{self._name}.sharded"),
                rank=key[1], world_size=key[2])
            self._durable_key = key
            self._durable_step = None
        return self._durable_store

    def _public(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._attrs}

    def _snapshot(self) -> None:
        self._saved = {k: _copy_leaf(v) for k, v in self._public().items()}

    def _maybe_load(self) -> bool:
        data = None
        path = _ckpt_path(self._name)
        if path is not None and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    data = pickle.load(f)
            except Exception:
                data = None
        if data is None:
            # pickle gone or torn (e.g. the host that wrote it is the
            # one that died): fall back to the durable sharded store
            store = self._durable()
            if store is not None:
                try:
                    data = store.restore_latest()
                    self._durable_step = store.latest_step()
                except Exception:
                    data = None
        if not isinstance(data, dict):
            return False
        for k, v in data.items():
            setattr(self, k, v)
            if k not in self._attrs:
                self._attrs.append(k)
        self._snapshot()
        return True

    def save(self) -> None:
        self._snapshot()
        path = _ckpt_path(self._name)
        if rank() == 0 and path is not None:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(self._saved, f)
            os.replace(tmp, path)
        store = self._durable()
        if store is not None:
            try:
                # drain a pending async failure NOW, attributed to the
                # save that caused it — submit() would re-raise it under
                # THIS commit's step number and silently drop this one
                store.check_error()
            except Exception:
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "an earlier durable commit of state %r failed in the "
                    "background", self._name, exc_info=True)
                try:
                    self._durable_step = max(self._durable_step or 0,
                                             store.latest_step() or 0)
                except Exception:
                    pass
            if self._durable_step is None:
                self._durable_step = store.latest_step() or 0
            self._durable_step += 1
            try:
                # async sharded commit: every rank writes its shard; the
                # train loop doesn't block on disk
                store.save(self._durable_step, self._saved)
            except Exception:
                # pickle (or host memory) still holds the commit; a
                # flaky shared filesystem must not kill training
                from horovod_tpu.common.logging import get_logger
                get_logger().warning(
                    "durable commit of state %r step %s failed",
                    self._name, self._durable_step, exc_info=True)
                # self-heal a desynced counter (e.g. this rank raced a
                # commit it read as not-yet-landed): next save targets
                # past everything already on disk
                try:
                    self._durable_step = max(self._durable_step,
                                             store.latest_step() or 0)
                except Exception:
                    pass

    def flush(self) -> None:
        """Drain pending DURABLE commits (they are async — the train loop
        never blocks on disk), so a worker about to exit knows its last
        commit actually landed.  A failed trailing commit is logged, not
        raised: the pickle tier and host memory still hold the state, and
        an exit path must not crash over a flaky shared filesystem."""
        store = self._durable_store
        if store is None:
            return
        try:
            store.wait()
        except Exception:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "flush: a trailing durable commit of state %r failed",
                self._name, exc_info=True)

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, _copy_leaf(v))
        self.on_reset()

    def sync(self) -> None:
        if size() > 1:
            from horovod_tpu.train.optimizer import broadcast_object
            # the durable step counter rides the same broadcast: every
            # rank must target the SAME next step or rank 0's commit
            # barrier waits on shards that never come (a fresh worker
            # reading latest_step() can lag an in-flight commit)
            step = self._durable_step
            if step is None:
                store = self._durable()
                if store is not None and rank() == 0:
                    step = store.latest_step() or 0
            data = broadcast_object(
                {"state": self._public(), "durable_step": step},
                root_rank=0, name=f"elastic.{self._name}")
            for k, v in data["state"].items():
                setattr(self, k, v)
            if data.get("durable_step") is not None:
                self._durable_step = int(data["durable_step"])
        self._snapshot()


def _copy_leaf(v: Any) -> Any:
    try:
        import jax
        if isinstance(v, jax.Array):
            return np.asarray(v).copy()
    except ImportError:
        pass
    if isinstance(v, np.ndarray):
        return v.copy()
    if isinstance(v, (dict, list, tuple)):
        return pickle.loads(pickle.dumps(v))
    return v


class TpuState(ObjectState):
    """Convenience for (params, opt_state, ...) pytrees of jax arrays —
    the analog of ``TorchState`` (``torch/elastic/state.py:27``)."""


def run(func: Callable) -> Callable:
    """Elastic run decorator (reference: ``run_fn``,
    ``common/elastic.py:151-175``): retry on HorovodInternalError with
    ``state.restore()``; resync on HostsUpdatedInterrupt."""

    def wrapper(state: State, *args: Any, **kwargs: Any):
        from horovod_tpu.elastic import remesh
        state.sync()
        while True:
            try:
                result = func(state, *args, **kwargs)
                # signed completion receipt: how a takeover driver that
                # adopted this (already running) worker learns the run
                # SUCCEEDED without ever having seen the exit code
                _publish_result()
                return result
            except HorovodInternalError:
                # re-mesh timeline (docs/OBSERVABILITY.md "Re-mesh
                # timeline"): the episode opens at the failure and
                # closes at the first completed step/commit of the new
                # world; each phase lands as a flight span and an
                # hvd_remesh_seconds{phase} observation
                ep = remesh.begin("internal_error", old_size=size())
                with remesh.phase("drain"):
                    state.restore()
                # peer death? the driver publishes the shrunken world as
                # soon as it reaps the dead process — re-rendezvous into
                # it IN PLACE (params stay in host memory, PID unchanged).
                # No doc inside the window -> transient op error: retry
                # in the same world like the reference.
                with remesh.phase("failure_detect"):
                    update = _await_world_update()
                if update is not None:
                    # the recovery world carries the trace the driver
                    # rooted for this reactive re-mesh — the episode's
                    # phases join it (docs/OBSERVABILITY.md)
                    try:
                        from horovod_tpu import tracing
                        ep.set_trace(tracing.child(
                            tracing.from_doc(update), "remesh"))
                    except Exception:
                        pass
                    _apply_world_update(update, force_shutdown=True)
                    with remesh.phase("restore"):
                        state.on_reset()
                        state.sync()
                    remesh.mark_recovered(
                        new_size=size(),
                        generation=int(update["generation"]))
                else:
                    # same-world retry: the mesh did not change, so
                    # this is NOT a re-mesh episode — close it with a
                    # retry marker (hvd_remesh_* must mean what it
                    # says, and already-emitted spans must not dangle)
                    remesh.note_same_world_retry()
                    state.sync()
            except HostsUpdatedInterrupt as e:
                # a world doc carrying a drain stamp is the PLANNED
                # re-mesh of the proactive preemption path
                # (docs/ELASTIC.md "Proactive drain & preemption"): the
                # doomed host announced itself, the driver published
                # around it, and detection cost ~nothing — record the
                # failure_detect phase anyway (≈0) so the
                # hvd_remesh_seconds series makes the planned-vs-
                # reactive difference a measured quantity, not a gap
                trigger = "preemption_drain" \
                    if isinstance(e.update, dict) and e.update.get("drain") \
                    else "hosts_updated"
                ep = remesh.begin(trigger, old_size=size())
                # the drain stamp carries the causal trace the notice/
                # finding rooted (a plain growth doc may carry a
                # doc-level one); this survivor's episode is a child
                # span of the driver's handling, so the whole chain —
                # finding → decision → action → drain → these phases →
                # first healthy step — shares one trace id
                try:
                    from horovod_tpu import tracing
                    src = e.update.get("drain") \
                        if trigger == "preemption_drain" else e.update
                    ep.set_trace(tracing.child(
                        tracing.from_doc(src), "remesh"))
                except Exception:
                    pass
                with remesh.phase("failure_detect"):
                    pass  # the doc arrived WITH the interrupt
                if trigger == "preemption_drain":
                    # the interrupt is only ever raised from commit()'s
                    # check_host_updates, so state.save() ran moments
                    # ago under the OLD world — while the doomed host is
                    # still alive (that is the whole point of advance
                    # notice).  What remains is to DRAIN any async
                    # durable commits to disk before the doomed worker
                    # exits, so its shard of the sharded store lands
                    # and the planned path hands the new world a
                    # complete checkpoint instead of hoping the pickle
                    # tier survives the host
                    try:
                        flush = getattr(state, "flush", None)
                        if callable(flush):
                            flush()
                    except Exception:
                        from horovod_tpu.common.logging import get_logger
                        get_logger().warning(
                            "final drain flush failed; continuing with "
                            "the last committed state", exc_info=True)
                if e.update is not None:
                    _apply_world_update(e.update)  # in-place re-mesh
                with remesh.phase("restore"):
                    state.on_reset()
                    state.sync()
                remesh.mark_recovered(
                    new_size=size(),
                    generation=int(e.update["generation"])
                    if e.update is not None else None)

    return wrapper
