"""Push-based worker notification channel.

Reference: ``horovod/runner/elastic/worker.py:46+``
(``WorkerNotificationService``/``WorkerNotificationManager``: every worker
runs a tiny HTTP listener and the driver pushes host-update requests to
it). With minutes-long TPU steps, the poll-at-commit design alone makes
growth-response latency equal to the commit interval; the push channel
delivers the driver's new world document the moment it is published, so
``state.commit()`` finds it locally (one in-process read, no driver
round-trip) and ``HostsUpdatedInterrupt`` fires at the very next commit.

Design: the worker listener IS a :class:`KVStoreServer` (the same HMAC'd
world-document bytes the driver publishes to its own KV are pushed into
the worker's local KV under ``world/current``), and workers register
their listener address in the driver KV under ``notify/<rank>``. The
driver pushes best-effort with short timeouts — the commit-time poll of
the driver KV remains as the fallback, so a lost push costs latency, not
correctness. Docs are HMAC-verified on the worker regardless of which
channel delivered them (the listener port is open to the network).

``HVD_ELASTIC_PUSH=0`` disables the listener (poll-only mode).
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional

from horovod_tpu.common.logging import get_logger

_lock = threading.Lock()
_listener: Optional["WorkerNotificationListener"] = None
_disabled = False
_registered_as = None  # (rank, generation) the listener is known to hold


class WorkerNotificationListener:
    """Per-worker push endpoint + registration with the driver KV.

    With the KV relay enabled (``HVD_TPU_KV_RELAY_ARITY`` > 0,
    docs/ELASTIC.md "Relayed control-plane KV") the listener doubles as
    this worker's RELAY NODE: children's world polls are served from its
    cache and their registrations forwarded up the tree, so the driver's
    root KV handles O(arity) sessions instead of O(world)."""

    def __init__(self, driver_addr: Optional[str] = None,
                 driver_port: Optional[int] = None) -> None:
        from horovod_tpu.runner import kv_relay
        self._driver = (driver_addr, driver_port)
        if kv_relay.relay_arity() > 0 and driver_addr is not None:
            self._kv = kv_relay.RelayKVServer(self._upstream)
        else:
            from horovod_tpu.runner.http_kv import KVStoreServer
            self._kv = KVStoreServer()
        self._kv.start()

    def _upstream(self):
        from horovod_tpu.runner import kv_relay
        addr, port = self._driver
        if addr is None:
            return None
        return kv_relay.client(addr, int(port))

    @property
    def port(self) -> int:
        return self._kv.port

    @property
    def kv(self):
        """The underlying KV server (relay diagnostics / tests)."""
        return self._kv

    def pending_raw(self) -> Optional[bytes]:
        """The most recently pushed world document (unvalidated bytes)."""
        return self._kv.get("world", "current")

    def register(self, driver_addr: str, driver_port: int) -> None:
        """Record ``notify/<rank> -> host:port`` in the driver KV so the
        driver knows where to push (host = this worker's slot hostname,
        which the driver can route to by construction).  Routed through
        the KV relay when enabled — the registration travels up the tree
        to the root, falling back to a direct root PUT."""
        from horovod_tpu.runner import kv_relay
        my_host = os.environ.get("HOROVOD_HOSTNAME") or socket.getfqdn()
        rank = os.environ.get("HOROVOD_RANK",
                              os.environ.get("HVD_TPU_RANK", "0"))
        # site label: registration failures show up on /metrics as their
        # own retry series, not blended into generic KV traffic — a
        # worker whose registrations keep exhausting is a worker the
        # driver will deem unrecoverable (docs/ELASTIC.md)
        kv_relay.client(driver_addr, driver_port).put(
            "notify", rank, f"{my_host}:{self.port}".encode(),
            timeout=5.0, site="elastic.notify.register")

    def stop(self) -> None:
        self._kv.stop()


def ensure_listener(driver_addr: str, driver_port: int) -> \
        Optional[WorkerNotificationListener]:
    """Start + register the singleton listener on first use; returns None
    when push is disabled or registration failed (poll-only fallback)."""
    global _listener, _disabled, _registered_as
    with _lock:
        if _disabled or os.environ.get("HVD_ELASTIC_PUSH", "1") == "0":
            return None
        if _listener is not None:
            # rank/generation changed (in-place recovery renumbered us and
            # the driver cleared its registrations): re-register the SAME
            # listener under the new identity so pushes — and the driver's
            # recovery-viability check — keep seeing this worker
            ident = _identity()
            if ident != _registered_as:
                try:
                    _listener.register(driver_addr, driver_port)
                    _registered_as = ident
                except OSError:
                    pass  # poll-at-commit still works
            return _listener
        try:
            listener = WorkerNotificationListener(driver_addr, driver_port)
            listener.register(driver_addr, driver_port)
        except OSError as e:
            # an unreachable driver KV or unbindable port must never break
            # training: fall back to poll-at-commit for the process's life
            get_logger().warning(
                "worker notification listener disabled (%s); falling back "
                "to poll-at-commit", e)
            _disabled = True
            try:
                listener.stop()
            except Exception:
                pass
            return None
        _listener = listener
        _registered_as = _identity()
        return _listener


def _identity():
    return (os.environ.get("HOROVOD_RANK",
                           os.environ.get("HVD_TPU_RANK", "0")),
            os.environ.get("HVD_ELASTIC_GENERATION", "0"))


def mark_stale() -> None:
    """Forget the registered identity WITHOUT tearing the listener down:
    the next ``ensure_listener`` re-registers the same endpoint.  Called
    when a driver takeover is detected (``elastic.outage``) — the fresh
    driver's KV starts with an empty ``notify`` scope, so the old
    registration exists only in a dead process's memory and pushes would
    silently stop until the worker re-announced itself."""
    global _registered_as
    with _lock:
        _registered_as = None


def current_listener() -> Optional[WorkerNotificationListener]:
    """The already-started listener, or None — never creates one (the
    cheap mid-step probe must not pay bind/registration latency)."""
    with _lock:
        return _listener


def reset_listener() -> None:
    """Tear down the singleton (tests / full shutdown)."""
    global _listener, _disabled, _registered_as
    with _lock:
        if _listener is not None:
            _listener.stop()
        _listener = None
        _disabled = False
        _registered_as = None
    from horovod_tpu.runner import kv_relay
    kv_relay.reset()
