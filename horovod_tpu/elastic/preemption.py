"""Proactive preemption drain: act on advance notice instead of timeout.

The reactive elastic path needs a host to DIE before anything happens —
survivors block in a collective until the transport deadline trips,
``HorovodInternalError`` fires, and the driver publishes a recovery
world (the ``failure_detect`` phase of the re-mesh timeline is bound by
the transport timeout).  But TPU pods *announce* maintenance and
preemption in advance (the GCE ``maintenance-event`` metadata surface),
SIGTERM-with-grace is the standard cloud eviction contract, and the
chaos harness can inject the same notice deterministically.  This
module turns those signals into a **planned** drain:

1. the :class:`PreemptionWatcher` (one daemon thread per worker, armed
   by ``hvd.init`` whenever an elastic driver manages the job) learns
   the host is doomed from one of three sources —
   ``runner/tpu_discovery.py`` metadata polling, an opt-in SIGTERM hook
   (``HVD_TPU_PREEMPTION_SIGTERM=1`` — off by default because the
   driver's own teardown speaks SIGTERM), or the chaos ``preemption``
   seam (docs/CHAOS.md);
2. it publishes a **drain notice** (``drain/<rank>``) through the
   driver KV (relay-routed, root fallback);
3. the driver plans a re-mesh around the doomed workers: survivors get
   a world doc stamped ``drain`` at their next commit (pushed — the
   ``failure_detect`` phase collapses to ~0), the doomed worker exits
   via the not-in-new-world path after its state was committed, and its
   slot is reserved for ``HVD_TPU_DRAIN_COOLDOWN_S`` before the host is
   re-admitted.  Drained workers are recorded ``DRAINED`` — never
   ``FAILURE``, never charged to ``host_crashes``, never blocklisted.

Every notice lands in the flight recorder (``preemption_notice``) and
on ``/metrics`` (``hvd_drain_notices_total{source=}``).  See
docs/ELASTIC.md "Proactive drain & preemption".
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Optional

from horovod_tpu.common.logging import get_logger
from horovod_tpu.common.safe_metrics import safe_inc as _metric

DEFAULT_POLL_S = 5.0

_lock = threading.Lock()
_watcher: Optional["PreemptionWatcher"] = None
_sigterm_installed = False
_prev_sigterm = None


def watch_enabled() -> bool:
    from horovod_tpu.common.config import env_bool
    return env_bool("PREEMPTION_WATCH", True)


def poll_interval_s() -> float:
    from horovod_tpu.common.config import env_float
    return max(0.05, env_float("PREEMPTION_POLL_S", DEFAULT_POLL_S))


def _identity():
    rank = os.environ.get("HOROVOD_RANK",
                          os.environ.get("HVD_TPU_RANK", "0"))
    host = os.environ.get("HOROVOD_HOSTNAME",
                          os.environ.get("HVD_TPU_HOSTNAME", "")) \
        or os.uname().nodename
    return rank, host


class PreemptionWatcher:
    """Polls the preemption signal sources and publishes ONE drain
    notice per doomed life (the flag survives re-meshes: a draining
    process stays draining until it exits)."""

    def __init__(self, poll_s: Optional[float] = None) -> None:
        self._poll_s = poll_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flag_lock = threading.Lock()
        self._notified = False
        # a notice whose KV publish failed transiently: retried on later
        # polls (the signal source itself may be one-shot — a chaos
        # marker rule, a SIGTERM — so the SOURCE is remembered here)
        self._retry_source: Optional[str] = None
        # metadata polling latches off after this many consecutive
        # failures — but ONLY when it has never once succeeded: off-TPU
        # there is no metadata server and each probe costs a connect
        # timeout.  On a real TPU VM (a probe has succeeded) a blip
        # must not permanently disable the primary production
        # preemption signal, so failures there just keep polling.
        self._metadata_failures = 0
        self._metadata_dead = False
        self._metadata_ok_once = False

    # -- signal sources -----------------------------------------------------
    def _chaos_notice(self) -> bool:
        try:
            from horovod_tpu import chaos
            applied = chaos.fire("preemption")
            return any(kind == "notice" for _seam, kind in applied)
        except Exception:
            return False

    def _metadata_notice(self) -> bool:
        if self._metadata_dead:
            return False
        from horovod_tpu.runner import tpu_discovery
        try:
            event = tpu_discovery.tpu_maintenance_event()
            self._metadata_failures = 0
            self._metadata_ok_once = True
            return event.strip().upper() not in (
                "", tpu_discovery.MAINTENANCE_NONE)
        except OSError:
            self._metadata_failures += 1
            if self._metadata_failures >= 3 and not self._metadata_ok_once:
                self._metadata_dead = True  # not on a TPU VM: stop paying
            return False

    def check_once(self) -> Optional[str]:
        """One poll round; returns the source of a NEW notice or None."""
        if self._notified:
            return None
        if self._retry_source:
            # an earlier publish failed transiently (e.g. the driver KV
            # restarting); keep retrying — the advance notice is only
            # worth something if it actually lands
            return self._retry_source
        if self._chaos_notice():
            return "chaos"
        if self._metadata_notice():
            return "metadata"
        return None

    # -- the notice ---------------------------------------------------------
    def notify(self, source: str) -> bool:
        """Publish the drain notice (idempotent per process life)."""
        with self._flag_lock:
            if self._notified:
                return False
            self._notified = True
        rank, host = _identity()
        get_logger().warning(
            "preemption notice (%s): publishing drain for rank %s on %s",
            source, rank, host)
        from horovod_tpu.runner import kv_relay
        try:
            endpoint = kv_relay.elastic_kv_endpoint()
        except ValueError as e:
            # a config bug, not a transient: retrying cannot help, and
            # this must not die as a debug-level line in the poll loop
            get_logger().warning(
                "drain notice has nowhere to go: %s — this process "
                "will be lost reactively", e)
            return False
        if endpoint is None:
            get_logger().warning(
                "drain notice has nowhere to go: no elastic driver KV "
                "(HVD_ELASTIC_KV) — this process will be lost reactively")
            return False
        addr, port_i = endpoint
        # causal tracing: the notice ROOTS a trace the driver handling,
        # the drain-stamped world, and every survivor's re-mesh episode
        # continue — "what caused this re-mesh" is one trace query
        from horovod_tpu import tracing
        nctx = tracing.new_trace("elastic")
        doc = {
            "rank": int(rank), "host": host, "source": source,
            # metadata maintenance dooms the whole HOST; a chaos or
            # SIGTERM notice targets this worker process
            "scope": "host" if source == "metadata" else "worker",
            "generation": int(os.environ.get("HVD_ELASTIC_GENERATION",
                                             "0")),
            "at": time.time()}
        if nctx is not None:
            doc[tracing.TRACEPARENT] = nctx.traceparent
        notice = json.dumps(doc).encode()
        try:
            from horovod_tpu.runner import kv_relay
            with tracing.activate(nctx):
                kv_relay.client(addr, port_i).put(
                    "drain", rank, notice, timeout=5.0,
                    site="elastic.drain_notice")
            self._retry_source = None
            # evidence is stamped only for a notice that actually
            # LANDED: the transient-failure path re-runs notify() every
            # poll, and counting each attempt would both inflate
            # hvd_drain_notices_total and churn useful history out of
            # the bounded flight ring
            try:
                from horovod_tpu.diagnostics.flight_recorder import \
                    record_event
                record_event("preemption_notice", source=source,
                             rank=rank, host=host,
                             **tracing.fields(nctx))
            except Exception:
                pass
            _metric("hvd_drain_notices_total",
                    "preemption/maintenance drain notices published, "
                    "per signal source", source=source)
            return True
        except OSError as e:
            # transient (the driver KV restarting, an injected blackout
            # window): un-latch so a later poll retries the PUBLISH —
            # the signal source may be one-shot, so it must not be
            # re-consulted, only the delivery re-attempted
            get_logger().warning(
                "drain notice publish failed (will retry): %s", e)
            with self._flag_lock:
                self._notified = False
                self._retry_source = source
            return False

    @property
    def draining(self) -> bool:
        return self._notified

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvd-tpu-preemption", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                source = self.check_once()
                if source is not None:
                    self.notify(source)
            except Exception:  # the watcher must never kill training
                get_logger().debug("preemption poll failed", exc_info=True)
            self._stop.wait(self._poll_s
                            if self._poll_s is not None
                            else poll_interval_s())

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def _on_sigterm(signum, frame) -> None:
    # The handler runs on the main thread between bytecodes — possibly
    # while that thread holds the metrics-registry or flight-recorder
    # lock inside a training step.  notify() acquires both, so running
    # it inline could deadlock the process on its own lock; publish
    # from a fresh thread instead (the handler itself only spawns).
    w = _watcher
    if w is not None:
        threading.Thread(target=w.notify, args=("sigterm",),
                         name="hvd-tpu-sigterm-drain",
                         daemon=True).start()
    if callable(_prev_sigterm):
        _prev_sigterm(signum, frame)


def _maybe_install_sigterm() -> None:
    """Opt-in (``HVD_TPU_PREEMPTION_SIGTERM=1``): SIGTERM publishes a
    drain notice and CONTINUES running until the planned re-mesh drops
    this worker.  Off by default — the elastic driver's own teardown
    delivers SIGTERM to the process group, and swallowing that would
    turn every generation restart into a hang-until-SIGKILL."""
    global _sigterm_installed, _prev_sigterm
    from horovod_tpu.common.config import env_bool
    if _sigterm_installed or not env_bool("PREEMPTION_SIGTERM", False):
        return
    if threading.current_thread() is not threading.main_thread():
        return  # signal.signal is main-thread-only
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        _sigterm_installed = True
    except (ValueError, OSError):
        pass


def ensure_watcher() -> Optional[PreemptionWatcher]:
    """Arm the singleton watcher (idempotent; called from ``hvd.init``).
    Only armed when an elastic driver manages this job — without a
    driver KV a drain notice has no consumer."""
    global _watcher
    if not watch_enabled() or not os.environ.get("HVD_ELASTIC_KV"):
        return None
    with _lock:
        if _watcher is None:
            _watcher = PreemptionWatcher()
            _watcher.start()
    _maybe_install_sigterm()
    return _watcher


def current_watcher() -> Optional[PreemptionWatcher]:
    return _watcher


def draining() -> bool:
    """Has this process published (or tried to publish) a drain notice?"""
    w = _watcher
    return w is not None and w.draining


def reset() -> None:
    """Tests: stop and drop the singleton and the SIGTERM hook."""
    global _watcher, _sigterm_installed, _prev_sigterm
    with _lock:
        w, _watcher = _watcher, None
    if w is not None:
        w.stop()
    if _sigterm_installed:
        try:
            signal.signal(signal.SIGTERM,
                          _prev_sigterm or signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        _sigterm_installed = False
        _prev_sigterm = None
