"""Re-mesh phase timeline: elastic recovery as a measured quantity.

ROADMAP item 5 asks for re-mesh time as a first-class metric — recovery
at scale should be seconds, and it should be *known* to be seconds, not
an anecdote.  This module instruments the worker-side recovery pipeline
(:mod:`horovod_tpu.elastic` ``run()`` / ``_apply_world_update`` and the
``hvd.init`` rendezvous split in ``common/basics.py``) into named
phases:

* ``failure_detect`` — from catching the failure to holding a new
  world document (dominated by the driver noticing the dead process
  and publishing; ~0 for a pushed growth doc);
* ``drain`` — rolling state back to the last commit + tearing the old
  core down;
* ``rendezvous`` — the new world's backend negotiation
  (``_create_backend`` inside ``hvd.init``);
* ``rebuild`` — the rest of re-init (process sets, timeline, mesh,
  exporter/fleet re-wiring);
* ``restore`` — re-applying/broadcasting elastic state into the new
  world (``on_reset`` + ``sync``);
* ``first_step`` — until the first completed step (or elastic commit)
  of the new world: the moment training is genuinely back.

Each phase lands three ways: a ``remesh_phase`` flight-recorder span as
it closes (live evidence even if the episode never completes), one
``hvd_remesh_seconds{phase=...}`` histogram observation per episode
(merged fleet-wide — the regression-gateable distribution), and a
summary point in the step time-series store rendered by
``python -m horovod_tpu.metrics history --remesh``.
``hvd_remesh_total`` counts completed episodes.

All entry points are cheap no-ops when no episode is active, and every
emission path is exception-proofed: the timeline must never make a
recovery WORSE.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

PHASES = ("failure_detect", "drain", "rendezvous", "rebuild", "restore",
          "first_step")

_LOCK = threading.Lock()
_EPISODE: Optional["Episode"] = None


class Episode:
    """One recovery episode: accumulates per-phase seconds, finishes at
    the first completed step of the new world."""

    def __init__(self, trigger: str, old_size: Optional[int] = None,
                 generation: Optional[int] = None) -> None:
        self.trigger = trigger
        self.old_size = old_size
        self.new_size: Optional[int] = None
        self.generation = generation
        # monotonic: an NTP step during recovery (host swaps make clock
        # adjustments likely exactly then) must not poison the
        # regression-gateable durations
        self.started_at = time.perf_counter()
        self.phases: Dict[str, float] = {}
        self._recovered_at: Optional[float] = None
        self.finished = False
        # causal tracing: a planned re-mesh continues the trace its
        # drain-stamped world doc carries (finding → decision → action
        # → drain → THESE phases); a reactive one roots its own
        self.trace = None

    def set_trace(self, ctx) -> None:
        """Adopt a trace context (the survivor's child span of the
        world doc's ``traceparent``); every phase emitted from here on
        is stamped with it.  Explicit stamping, not thread-local
        activation: recovery spans several threads."""
        self.trace = ctx

    def _trace_fields(self) -> Dict[str, str]:
        return self.trace.fields() if self.trace is not None else {}

    def add_phase(self, name: str, seconds: float) -> None:
        self.phases[name] = self.phases.get(name, 0.0) + float(seconds)
        _record_flight("remesh_phase", phase=name,
                       seconds=round(seconds, 4), trigger=self.trigger,
                       **self._trace_fields())

    def mark_recovered(self) -> None:
        """The new world is up and state is restored: the clock on
        ``first_step`` starts now."""
        self._recovered_at = time.perf_counter()

    def finish(self, complete: bool = True) -> None:
        if self.finished:
            return
        self.finished = True
        if complete and self._recovered_at is not None:
            self.add_phase("first_step",
                           time.perf_counter() - self._recovered_at)
        total = time.perf_counter() - self.started_at
        try:
            from horovod_tpu.metrics.registry import default_registry
            reg = default_registry()
            # only COMPLETED episodes feed the histogram: partial
            # phase times from an abandoned recovery (a retry storm)
            # would smear the regression-gateable distribution, and
            # with no matching hvd_remesh_total tick the per-episode
            # contract breaks.  Abandoned evidence still lands in the
            # remesh_abandoned flight event + the time-series point.
            if complete:
                for name, secs in self.phases.items():
                    reg.histogram(
                        "hvd_remesh_seconds",
                        help="elastic re-mesh recovery time per phase",
                        labels={"phase": name}).observe(secs)
                reg.counter(
                    "hvd_remesh_total",
                    help="completed elastic re-mesh recoveries").inc()
        except Exception:
            pass
        if complete:
            # goodput ledger: a completed recovery's total is this
            # window's remesh_recovery charge (abandoned episodes roll
            # into the episode that finally completes)
            try:
                from horovod_tpu.metrics import goodput
                goodput.note_remesh(total)
            except Exception:
                pass
        # a driver takeover that healed inside this episode's window is
        # part of its story: `history --remesh` marks such episodes, and
        # the chaos acceptance for the mid-re-mesh driver kill asserts
        # the timeline shows a TAKEOVER, not a second generation restart
        took = _spanned_takeover(self.started_at)
        _record_flight("remesh_complete" if complete
                       else "remesh_abandoned",
                       trigger=self.trigger, total_s=round(total, 4),
                       old_size=self.old_size, new_size=self.new_size,
                       generation=self.generation,
                       **({"takeover": True} if took else {}),
                       **self._trace_fields(),
                       **{f"{k}_s": round(v, 4)
                          for k, v in self.phases.items()})
        try:
            from horovod_tpu.metrics import timeseries
            timeseries.record_point({
                "remesh": {k: round(v, 4)
                           for k, v in self.phases.items()},
                "remesh_total_s": round(total, 4),
                "trigger": self.trigger,
                "old_size": self.old_size, "new_size": self.new_size,
                "generation": self.generation,
                "complete": complete,
                **({"takeover": True} if took else {}),
                **self._trace_fields()})
        except Exception:
            pass
        try:
            # the episode as proper spans (one parent, one child per
            # phase laid out in pipeline order — starts are
            # approximate, durations measured): what `diagnostics
            # trace <id>` renders as the recovery subtree
            from horovod_tpu import tracing
            if self.trace is not None:
                end_wall = time.time()
                tracing.record_span(
                    "remesh", f"remesh_{self.trigger}", self.trace,
                    start=end_wall - total, dur_s=total,
                    old_size=self.old_size, new_size=self.new_size,
                    generation=self.generation, complete=complete)
                t = end_wall - total
                for name in PHASES:
                    if name in self.phases:
                        dur = self.phases[name]
                        tracing.record_span(
                            "remesh", name,
                            tracing.child(self.trace, "remesh"),
                            start=t, dur_s=dur)
                        t += dur
        except Exception:
            pass
        try:
            from horovod_tpu.common.logging import get_logger
            breakdown = " ".join(f"{k}={v:.3f}s"
                                 for k, v in self.phases.items())
            get_logger().info("re-mesh %s in %.3fs (%s): %s",
                              "recovered" if complete else "abandoned",
                              total, self.trigger, breakdown)
        except Exception:
            pass


def _spanned_takeover(started_at: float) -> bool:
    """True when a driver takeover recovered inside the window that
    started at ``started_at`` (a ``perf_counter`` stamp): the episode's
    recovery rode through a control-plane crash."""
    try:
        from horovod_tpu.elastic import outage
        rec = outage.last_recovery_perf()
        return rec is not None and rec >= started_at
    except Exception:
        return False


def _record_flight(kind: str, **fields) -> None:
    try:
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(kind, **{k: v for k, v in fields.items()
                              if v is not None})
    except Exception:
        pass


# -- module seams -------------------------------------------------------------
def begin(trigger: str, old_size: Optional[int] = None,
          generation: Optional[int] = None) -> Episode:
    """Open a recovery episode (closing — as abandoned — any episode a
    previous failure left unfinished: back-to-back failures are one
    re-mesh each, not one giant smeared episode)."""
    global _EPISODE
    with _LOCK:
        prev, _EPISODE = _EPISODE, None
    if prev is not None and not prev.finished:
        prev.finish(complete=False)
    ep = Episode(trigger, old_size=old_size, generation=generation)
    with _LOCK:
        _EPISODE = ep
    return ep


def current() -> Optional[Episode]:
    return _EPISODE


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Measure a recovery phase; a plain pass-through when no episode
    is active (the same code paths run for a first init)."""
    ep = _EPISODE
    if ep is None or ep.finished:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ep.add_phase(name, time.perf_counter() - t0)


def mark_recovered(new_size: Optional[int] = None,
                   generation: Optional[int] = None) -> None:
    ep = _EPISODE
    if ep is None or ep.finished:
        return
    if new_size is not None:
        ep.new_size = new_size
    if generation is not None:
        ep.generation = generation
    ep.mark_recovered()
    if new_size is not None and ep.old_size is not None \
            and new_size != ep.old_size:
        # the world genuinely changed shape: surface it as an external
        # finding so the autopilot's topology policy can invalidate the
        # plan cache + re-tune (docs/OBSERVABILITY.md "Autopilot").  A
        # same-size recovery (replacement respawned) keeps the cached
        # plans — they are keyed by the world fingerprint and still
        # describe this topology.
        try:
            from horovod_tpu.metrics.anomaly import report_finding
            report_finding("world_changed", old_size=ep.old_size,
                           new_size=new_size, generation=generation,
                           trigger=ep.trigger)
        except Exception:
            pass


def note_step_end(step: Optional[int] = None) -> None:
    """A training step (or elastic commit) completed: if an episode is
    waiting on its first step, close it.  Called from
    ``StepTimer.end_step`` and ``State.commit`` — whichever the loop
    uses fires first; cheap no-op otherwise."""
    global _EPISODE
    ep = _EPISODE
    if ep is None or ep.finished or ep._recovered_at is None:
        return
    with _LOCK:
        if _EPISODE is ep:
            _EPISODE = None
    ep.finish(complete=True)


def note_same_world_retry() -> None:
    """A transient failure resolved into the SAME world: not a re-mesh
    episode (``hvd_remesh_*`` must mean what it says), but the phases
    already emitted live need a terminal marker — a flight-ring reader
    must not see ``remesh_phase`` spans that simply vanish."""
    global _EPISODE
    with _LOCK:
        ep, _EPISODE = _EPISODE, None
    if ep is None:
        return
    ep.finished = True
    _record_flight("remesh_retry", trigger=ep.trigger,
                   total_s=round(time.perf_counter() - ep.started_at, 4))


def reset() -> None:
    """Tests: drop any open episode without emitting."""
    global _EPISODE
    with _LOCK:
        ep, _EPISODE = _EPISODE, None
    if ep is not None:
        ep.finished = True
