"""Process-level communication backends for eager collectives.

This is the seam the reference fills with its OperationManager priority list
(``horovod/common/operations.cc:144-253``: MPI-GPU/NCCL/Gloo/CCL → first
``Enabled()`` op wins). TPU-native equivalents:

* :class:`LocalBackend` — single process; collectives are identities over one
  contributor (the reference behaves the same when run without a launcher).
* ``CoreBackend`` (:mod:`horovod_tpu.core.bindings`) — the C++ negotiation
  core with TCP host collectives, the "Gloo-class" reference plane.
* ``XlaBackend`` (:mod:`horovod_tpu.ops.xla_backend`) — multi-host data plane:
  collectives ride ICI/DCN as jitted XLA ops over the global mesh, ordered by
  the C++ controller.

Every backend exposes async enqueue + handle semantics mirroring the
reference's ``EnqueueTensorAllreduce`` + ``handle_manager``
(``horovod/torch/mpi_ops_v2.cc:89-127,566-580``).
"""

from __future__ import annotations

import abc
import threading
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from horovod_tpu.ops.reduce_op import ReduceOp


class HvdHandle:
    """Async completion handle (reference: ``HandleManager``,
    ``horovod/torch/handle_manager.{h,cc}``)."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None

    def _set_result(self, value: Any) -> None:
        self._result = value
        self._event.set()
        self._fire_done(True)

    def _set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()
        self._fire_done(False)

    def add_done_callback(self, cb) -> None:
        """Invoke ``cb(ok: bool)`` once when the handle completes (fires
        immediately if it already has). Used by the diagnostics layer to
        flight-record collective completion; callbacks must not raise —
        errors are swallowed so observability can never fail a wait."""
        self._done_cb = cb
        if self._event.is_set():
            self._fire_done(self._error is None)

    def _fire_done(self, ok: bool) -> None:
        # dict.pop is atomic under the GIL: when completion and
        # add_done_callback race, exactly one caller wins the pop
        cb = self.__dict__.pop("_done_cb", None)
        if cb is not None:
            try:
                cb(ok)
            except Exception:
                pass

    def poll(self) -> bool:
        """Reference: ``PollHandle`` (``mpi_ops_v2.cc:566-571``)."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Reference: ``WaitAndClear`` (``mpi_ops_v2.cc:573-580``)."""
        if not self._event.wait(timeout):
            raise TimeoutError("collective did not complete in time")
        if self._error is not None:
            raise self._error
        return self._result

    @staticmethod
    def done(value: Any) -> "HvdHandle":
        h = HvdHandle()
        h._set_result(value)
        return h


class Backend(abc.ABC):
    """Process-group communicator over ``ranks`` (None = all)."""

    def __init__(self, rank: int, size: int) -> None:
        self.rank = rank
        self.size = size

    # -- collectives (async; return HvdHandle yielding the result array) ----
    @abc.abstractmethod
    def allreduce_async(self, name: str, value, op: ReduceOp,
                        prescale: float = 1.0, postscale: float = 1.0
                        ) -> HvdHandle: ...

    @abc.abstractmethod
    def grouped_allreduce_async(self, names: Sequence[str], values: Sequence,
                                op: ReduceOp, prescale: float = 1.0,
                                postscale: float = 1.0) -> HvdHandle: ...

    @abc.abstractmethod
    def allgather_async(self, name: str, value) -> HvdHandle: ...

    @abc.abstractmethod
    def broadcast_async(self, name: str, value, root_rank: int) -> HvdHandle: ...

    @abc.abstractmethod
    def alltoall_async(self, name: str, value,
                       splits: Optional[Sequence[int]] = None) -> HvdHandle: ...

    def reducescatter_async(self, name: str, value, op: ReduceOp) -> HvdHandle:
        """Default: allreduce then take this rank's dim-0 slice. Backends with
        a native reduce-scatter (XLA ``psum_scatter``) override this."""
        h = self.allreduce_async(name, value, op)
        out = HvdHandle()

        def finish():
            try:
                full = h.wait()
                n = self.size
                rows = np.asarray(full).shape[0]
                if rows % n != 0:
                    raise ValueError(
                        f"reducescatter: leading dim {rows} not divisible by "
                        f"process-set size {n}")
                chunk = rows // n
                out._set_result(full[self.rank * chunk:(self.rank + 1) * chunk])
            except BaseException as e:  # propagate to waiter
                out._set_error(e)

        threading.Thread(target=finish, daemon=True).start()
        return out

    @abc.abstractmethod
    def barrier(self) -> None: ...

    def join(self, device: int = -1) -> int:
        """Reference Join op (``EnqueueJoin``, ``operations.cc:1714-1742``):
        declare this rank out of data; returns rank of the last joiner.

        Raises by default: join needs dynamic negotiation, and a backend
        that silently pretends to support it deadlocks the OTHER ranks
        (they keep waiting for collectives the joined rank never submits).
        Backends that can negotiate (CoreBackend) or where join is trivial
        (LocalBackend) override this."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support hvd.join(); use the "
            "TCP core backend (unset HOROVOD_TPU_OPERATIONS) for "
            "join-style uneven data")

    # -- observability ------------------------------------------------------
    def counters(self) -> dict:
        """Control-plane counters (cache-hit rate, negotiation volume,
        fusion effectiveness). Backends without a negotiating control
        plane have nothing to report."""
        return {}

    def start_core_timeline(self, file_path: str,
                            mark_cycles: bool = False) -> bool:
        """Dynamically start the backend's native timeline (reference:
        ``horovod_start_timeline``, ``operations.cc:1011-1041``). Returns
        True if the backend owns the timeline file (Python layer must then
        NOT open it too — one writer per path)."""
        return False

    def stop_core_timeline(self) -> bool:
        return False

    # -- lifecycle ----------------------------------------------------------
    @abc.abstractmethod
    def make_subset(self, ranks: Sequence[int]) -> "Backend": ...

    def shutdown(self) -> None:
        pass


def check_scale_dtype(dtype, factor: float) -> None:
    """Reject fractional pre/postscale on integral tensors (the reference
    rejects non-float scaling); shared by the single and grouped paths."""
    if factor != 1.0 and np.issubdtype(dtype, np.integer) \
            and float(factor) != int(factor):
        raise ValueError(
            f"prescale/postscale factor {factor} is fractional but the "
            f"tensor dtype is integral ({dtype}); cast to float first "
            "(matches the reference rejecting non-float scaling).")


def _scale(arr, factor: float):
    if factor == 1.0:
        return arr
    check_scale_dtype(np.asarray(arr).dtype, factor)
    return (arr * factor).astype(np.asarray(arr).dtype)


class LocalBackend(Backend):
    """Single-contributor group: every collective is (scaled) identity.

    Matches reference behavior with ``size() == 1`` — e.g. allreduce returns
    the tensor itself after pre/postscale, allgather returns the input,
    broadcast requires root 0.
    """

    def __init__(self, rank: int = 0, size: int = 1) -> None:
        assert size == 1
        super().__init__(rank, size)

    def allreduce_async(self, name, value, op, prescale=1.0, postscale=1.0):
        out = _scale(_scale(value, prescale), postscale)
        if op == ReduceOp.AVERAGE:
            pass  # average over one contributor
        return HvdHandle.done(out)

    def grouped_allreduce_async(self, names, values, op,
                                prescale=1.0, postscale=1.0):
        outs = [_scale(_scale(v, prescale), postscale) for v in values]
        return HvdHandle.done(outs)

    def allgather_async(self, name, value):
        return HvdHandle.done(value)

    def broadcast_async(self, name, value, root_rank):
        if root_rank != self.rank:
            raise ValueError(
                f"broadcast root_rank={root_rank} out of range for size 1")
        return HvdHandle.done(value)

    def alltoall_async(self, name, value, splits=None):
        if splits is None:
            recv_splits = np.asarray([np.asarray(value).shape[0]],
                                     dtype=np.int32)
        else:
            splits = np.asarray(splits, dtype=np.int32)
            if splits.shape != (1,):
                raise ValueError("alltoall splits must have one entry per rank")
            recv_splits = splits
        return HvdHandle.done((value, recv_splits))

    def barrier(self) -> None:
        return

    def join(self, device: int = -1) -> int:
        return 0  # sole contributor: this rank is the last joiner

    def make_subset(self, ranks):
        return LocalBackend(0, 1)


def make_backend(state) -> Backend:
    """Priority selection (reference: ``CreateOperationManager``,
    ``operations.cc:144-253``).

    The decision keys off the LAUNCHED world size: a process restricted to a
    1-rank global set by ``init(ranks=[r])`` in a multi-process launch must
    still join the core world so the other processes' rendezvous completes.
    """
    if getattr(state, "launched_size", state.size) <= 1:
        return LocalBackend(state.rank, 1)
    # Multi-process. HVD_TPU_OPERATIONS=XLA_EAGER selects the XLA data
    # plane (jitted collectives over the global mesh via jax.distributed);
    # default is the C++ core (TCP controller + host collectives), which
    # additionally negotiates dynamic submission order.
    if state.config is not None and \
            state.config.tpu_operations == "XLA_EAGER":
        from horovod_tpu.ops.xla_backend import XlaBackend
        return XlaBackend(state)
    from horovod_tpu.core.bindings import core_backend_or_raise
    return core_backend_or_raise(state)
