"""Flash attention as a Pallas TPU kernel — the hot op of the transformer
path.

No reference analog (the reference's only kernel is a batched-memcpy .cu,
``horovod/common/ops/cuda/cuda_kernels.cu``); on TPU the analogous "write
the hot loop yourself" target is attention. The kernel streams K/V blocks
through VMEM while Q stays resident, maintaining the flash running-softmax
(m, l, acc) in VMEM scratch so HBM traffic is O(S·D) instead of O(S²):

  grid = (batch·heads, Sq/BLOCK_Q, Sk/BLOCK_K)   — K-block innermost
  per (q-block): for each k-block: s = q @ kᵀ; online-softmax update

The kernel is DIFFERENTIABLE: a ``jax.custom_vjp`` pairs the forward
kernel (which also emits the per-row log-sum-exp residual) with a
blockwise backward pass that recomputes attention probabilities one
K-block at a time from (q, k, v, o, lse) — the standard flash-attention
backward (Dao et al.), memory-bounded at O(S·block_k) instead of O(S²),
so training through the kernel never materializes the score matrix.

Falls back to the pure-XLA implementation on CPU or when shapes don't meet
TPU tiling constraints (last dim 128-multiple, block-divisible sequence).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, scale: float, causal: bool, block_q: int,
                  block_k: int):
    """One (q-block, k-block) step; grid (BH, nq, nk) with k innermost."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)

    @pl.when(kv_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def body():
        q = q_ref[0].astype(jnp.float32)           # [bq, D]
        k = k_ref[0].astype(jnp.float32)           # [bk, D]
        v = v_ref[0].astype(jnp.float32)           # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_cur = jnp.max(s, axis=-1)[:, None]       # [bq, 1]
        m_next = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_next)                    # [bq, bk]
        alpha = jnp.exp(m_prev - m_next)
        l_next = l_prev * alpha + jnp.sum(p, -1)[:, None]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_next
        l_ref[:] = l_next

    if causal:
        # skip fully-masked k-blocks (strictly above the diagonal)
        @pl.when(kv_idx * block_k <= q_idx * block_q + block_q - 1)
        def _run():
            body()
    else:
        body()

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # log-sum-exp residual for the backward pass: lse = m + log(l)
        lse_ref[0] = (m_ref[:] + jnp.log(l_safe))[:, 0]


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    """Run the kernel; q/k/v [B, S, H, D] → (o [B, S, H, D], lse [BH, Sq])."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    # layout: fold batch & heads; blocks over sequence
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)

    nq = Sq // block_q
    nk = Sk // block_k
    grid = (B * H, nq, nk)

    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # m (running max)
            pltpu.VMEM((block_q, 1), jnp.float32),   # l (running sum)
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                           interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                             interpret)
    return (o, lse), (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, cts):
    """Blockwise flash backward (Dao et al.): recompute p = exp(s - lse)
    one K-block at a time; dv = pᵀdo, ds = p⊙(do·vᵀ − Δ + dlse), dq +=
    ds·k, dk = dsᵀq. Peak extra memory O(Sq·block_k) per (batch·head).
    The lse cotangent enters through ∂lse/∂s_j = p_j (lse is the row
    log-partition), which is what makes the (o, lse) pair usable as a
    mergeable partial result (ring attention)."""
    do, dlse = cts
    q, k, v, o, lse = res
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    bk = block_k
    nk = Sk // bk

    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D).astype(jnp.float32)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Sk, D).astype(jnp.float32)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Sk, D).astype(jnp.float32)
    of = o.transpose(0, 2, 1, 3).reshape(B * H, Sq, D).astype(jnp.float32)
    dof = do.transpose(0, 2, 1, 3).reshape(B * H, Sq, D).astype(jnp.float32)

    if dlse is None:
        dlse = jnp.zeros_like(lse)
    # ds = p ⊙ (dp − Δ + dlse): fold the lse cotangent into the row term
    adj = jnp.sum(dof * of, axis=-1) - dlse.astype(jnp.float32)  # [BH, Sq]

    dq = jnp.zeros_like(qf)
    dk = jnp.zeros_like(kf)
    dv = jnp.zeros_like(vf)

    if causal and nk <= 64:
        # Statically-unrolled loop with per-block row restriction: K-block
        # j only reaches q rows >= j*bk (the rest are masked in the
        # forward), so slicing the q side halves the backward FLOPs —
        # mirroring the forward kernel's diagonal block-skip. Unrolling is
        # bounded (<= 64 blocks) to keep compile time sane; longer
        # sequences take the dynamic full-row loop below.
        for j in range(nk):
            r0 = j * bk                                     # first live row
            qs, dos = qf[:, r0:], dof[:, r0:]
            kb, vb = kf[:, r0:r0 + bk], vf[:, r0:r0 + bk]
            s = jnp.einsum("bqd,bkd->bqk", qs, kb) * scale  # [BH,Sq-r0,bk]
            qpos = r0 + jnp.arange(Sq - r0)
            kpos = r0 + jnp.arange(bk)
            s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
            p = jnp.exp(s - lse[:, r0:, None])
            dvb = jnp.einsum("bqk,bqd->bkd", p, dos)
            dp = jnp.einsum("bqd,bkd->bqk", dos, vb)
            ds = p * (dp - adj[:, r0:, None]) * scale
            dq = dq.at[:, r0:].add(jnp.einsum("bqk,bkd->bqd", ds, kb))
            dk = dk.at[:, r0:r0 + bk].set(
                jnp.einsum("bqk,bqd->bkd", ds, qs))
            dv = dv.at[:, r0:r0 + bk].set(dvb)
    else:
        qpos = jnp.arange(Sq)

        def block(j, carry):
            dq, dk, dv = carry
            kb = lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)
            vb = lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
            s = jnp.einsum("bqd,bkd->bqk", qf, kb) * scale  # [BH,Sq,bk]
            if causal:
                kpos = j * bk + jnp.arange(bk)
                s = jnp.where(qpos[:, None] >= kpos[None, :], s, NEG_INF)
            p = jnp.exp(s - lse[..., None])                 # [BH,Sq,bk]
            dvb = jnp.einsum("bqk,bqd->bkd", p, dof)
            dp = jnp.einsum("bqd,bkd->bqk", dof, vb)
            ds = p * (dp - adj[..., None]) * scale
            dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kb)
            dkb = jnp.einsum("bqk,bqd->bkd", ds, qf)
            dk = lax.dynamic_update_slice_in_dim(dk, dkb, j * bk, axis=1)
            dv = lax.dynamic_update_slice_in_dim(dv, dvb, j * bk, axis=1)
            return dq, dk, dv

        dq, dk, dv = lax.fori_loop(0, nk, block, (dq, dk, dv))

    def unfold(x, S):
        return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)

    return (unfold(dq, Sq).astype(q.dtype), unfold(dk, Sk).astype(k.dtype),
            unfold(dv, Sk).astype(v.dtype))


_flash_lse.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = True,
                             scale: Optional[float] = None,
                             block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                             interpret: bool = False):
    """Flash attention returning ``(o, lse)``: the normalized output plus
    the per-row log-partition (``lse`` shaped ``[B*H, Sq]``). The pair is
    a mergeable partial softmax — two results over disjoint key sets
    combine exactly via logaddexp (ring attention's per-step merge).
    Differentiable in both outputs."""
    D = q.shape[-1]
    scale = float(scale) if scale is not None else float(1.0 / (D ** 0.5))
    return _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret)


def flash_attention_tpu(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None,
                        block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                        interpret: bool = False) -> jax.Array:
    """q/k/v: [B, S, H, D] → [B, S, H, D]. Requires S % block == 0 and
    D % 128 == 0 (use :func:`attend` for the auto-fallback wrapper).
    Differentiable (custom VJP with blockwise recompute backward)."""
    D = q.shape[-1]
    scale = float(scale) if scale is not None else float(1.0 / (D ** 0.5))
    return _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret)[0]


def attend(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
           scale: Optional[float] = None) -> jax.Array:
    """Attention with automatic kernel selection: the Pallas flash kernel on
    TPU when shapes satisfy its tiling constraints, else the fused-XLA
    fallback. Differentiable on both paths."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    on_tpu = jax.default_backend() == "tpu"
    ok = (D % 128 == 0 and Sq % BLOCK_Q == 0 and Sk % BLOCK_K == 0)
    if on_tpu and ok:
        return flash_attention_tpu(q, k, v, causal, scale)
    from horovod_tpu.parallel.ring_attention import _plain_attention
    return _plain_attention(q, k, v, causal, scale)
