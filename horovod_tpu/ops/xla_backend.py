"""XLA data-plane backend: eager multi-process collectives executed as
jitted XLA collectives over the global mesh (ICI within a slice, DCN/gloo
across hosts).

This is the reference's NCCL role (SURVEY.md §2.7: "NCCL → ICI collectives
via jitted XLA ops over the pod slice") for the EAGER path: per-process
arrays become shards of a global array and one cached jitted shard_map
program moves the bytes — no host round-trip through the TCP rings.

Async contract (reference: enqueue + ``handle_manager`` callbacks,
``horovod/torch/mpi_ops_v2.cc:89-127``): every ``*_async`` returns
immediately with a pending :class:`HvdHandle`; a dedicated dispatch thread
executes submissions in FIFO order and completes the handles. The FIFO is
shared across the global set and every process set, so each process has a
single total submission order (members of overlapping sets must submit the
shared sets' ops in a consistent order — the same-order contract).

Fusion (reference: ``nccl_operations.cc:156-214`` fuse→reduce→unfuse):
``grouped_allreduce_async`` compiles ONE program that concatenates the
group per dtype, reduces each fused buffer with a single collective, and
splits the results — N tensors, one collective launch per dtype.

Contract: every member process must issue the same collectives in the same
order (the standard data-parallel training pattern, and exactly what the
reference's response cache converges to in steady state). For dynamically
ordered submissions use the TCP core backend, which negotiates ordering.
Select with ``HVD_TPU_OPERATIONS=XLA_EAGER`` (reference knob analog:
``HOROVOD_CPU_OPERATIONS``/compile-time ``HOROVOD_GPU_ALLREDUCE``).
"""

from __future__ import annotations

import functools
import os
import queue
import threading
from typing import List, Optional, Sequence

import numpy as np

from horovod_tpu._compat import shard_map
from horovod_tpu.ops.backend import Backend, HvdHandle, _scale
from horovod_tpu.ops.reduce_op import ReduceOp

_DIST_LOCK = threading.Lock()
_DIST_INITIALIZED = False


def _ensure_jax_distributed(coord_addr: str, port: int, size: int,
                            rank: int) -> None:
    global _DIST_INITIALIZED
    with _DIST_LOCK:
        if _DIST_INITIALIZED:
            return
        import jax
        jax.distributed.initialize(
            coordinator_address=f"{coord_addr}:{port}",
            num_processes=size, process_id=rank)
        _DIST_INITIALIZED = True


class _Dispatcher:
    """FIFO dispatch thread completing pending handles (the reference's
    background-loop + finalizer-thread role, ``gpu_operations.h:100-137``)."""

    def __init__(self) -> None:
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-xla-dispatch")
        self._thread.start()

    def submit(self, fn) -> HvdHandle:
        h = HvdHandle()
        self._q.put((fn, h))
        return h

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, h = item
            try:
                h._set_result(fn())
            except BaseException as e:  # complete the handle, keep looping
                h._set_error(e)

    def shutdown(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=30)


class _XlaGroup:
    """Collective programs over one process group (global set or a process
    set): a 'proc' mesh with one device per member process and a compiled-
    program cache keyed like the reference's per-set NCCL comm cache
    (``nccl_operations.cc:65-107``)."""

    def __init__(self, jax_mod, devices, group_rank: int) -> None:
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        self._jax = jax_mod
        self._jnp = jnp
        self._P = P
        self._NS = NamedSharding
        self._mesh = Mesh(np.asarray(devices), ("proc",))
        self.rank = group_rank
        self.size = len(devices)
        self._fn_cache = {}
        self._ragged_ok: Optional[bool] = None

    # -- data movement -------------------------------------------------------
    def to_global(self, arr: np.ndarray):
        """Per-process contribution → global array [size, ...] sharded over
        'proc'."""
        jax = self._jax
        sharding = self._NS(self._mesh, self._P("proc"))
        row = np.asarray(arr)[None]
        my_dev = self._mesh.devices[self.rank]
        shards = [jax.device_put(row, my_dev)]
        return jax.make_array_from_single_device_arrays(
            (self.size,) + np.asarray(arr).shape, sharding, shards)

    def local_view(self, garr) -> np.ndarray:
        return np.asarray(garr.addressable_shards[0].data)

    # -- compiled programs ---------------------------------------------------
    def collective(self, kind: str, op: ReduceOp, shape, dtype, extra=()):
        key = (kind, op, tuple(shape), str(dtype), tuple(extra))
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        jax, jnp, P = self._jax, self._jnp, self._P
        mesh = self._mesh
        from horovod_tpu.ops.mesh_collectives import preduce

        if kind == "allreduce":
            @functools.partial(shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P(), check_vma=False)
            def body(x):
                return preduce(x[0], "proc", op)
        elif kind == "allgather":
            @functools.partial(shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P(), check_vma=False)
            def body(x):
                return jax.lax.all_gather(x[0], "proc", axis=0, tiled=True)
        elif kind == "broadcast":
            (root,) = extra

            @functools.partial(shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P(), check_vma=False)
            def body(x):
                idx = jax.lax.axis_index("proc")
                masked = jnp.where(idx == root, x[0],
                                   jnp.zeros_like(x[0]))
                # psum promotes bool -> int; cast back to the input dtype
                return jax.lax.psum(masked, "proc").astype(x.dtype)
        elif kind == "alltoall":
            @functools.partial(shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P("proc"), check_vma=False)
            def body(x):
                return jax.lax.all_to_all(x, "proc", split_axis=1,
                                          concat_axis=0, tiled=False)
        else:
            raise ValueError(kind)
        fn = jax.jit(body)
        self._fn_cache[key] = fn
        return fn

    def grouped_allreduce_program(self, op: ReduceOp, shapes, dtypes,
                                  prescale: float, postscale: float):
        """ONE program for the whole group: concat per dtype → a single
        collective per fused buffer → split (the fusion contract,
        reference ``nccl_operations.cc:170-211`` fuse→reduce→unfuse)."""
        key = ("grouped", op, tuple(map(tuple, shapes)),
               tuple(str(d) for d in dtypes), float(prescale),
               float(postscale))
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        jax, jnp, P = self._jax, self._jnp, self._P
        from horovod_tpu.ops.mesh_collectives import preduce

        n = len(shapes)
        by_dtype: dict = {}
        for i, d in enumerate(dtypes):
            by_dtype.setdefault(str(d), []).append(i)

        from horovod_tpu.ops.reduce_op import ReduceOp as _R

        @functools.partial(shard_map, mesh=self._mesh,
                           in_specs=tuple(P("proc") for _ in range(n)),
                           out_specs=tuple(P() for _ in range(n)),
                           check_vma=False)
        def body(*xs):
            outs: List = [None] * n
            for _, idxs in sorted(by_dtype.items()):
                flats = [xs[i][0].reshape(-1) for i in idxs]
                fused = flats[0] if len(flats) == 1 else \
                    jnp.concatenate(flats)
                if prescale != 1.0:
                    fused = (fused * prescale).astype(fused.dtype)
                if op == _R.ADASUM:
                    # one gather for the fused buffer, but PER-TENSOR
                    # scaled-add coefficients — the reference computes
                    # per-layer dots inside the fused buffer
                    # (adasum.h tensor_counts), as does the C++ core
                    from horovod_tpu.ops.adasum import adasum_tree_reduce
                    gathered = jax.lax.all_gather(fused, "proc")
                    off = 0
                    parts = []
                    for i in idxs:
                        sz = int(np.prod(shapes[i], dtype=np.int64))
                        parts.append(adasum_tree_reduce(
                            jax.lax.dynamic_slice_in_dim(gathered, off, sz,
                                                         axis=1)))
                        off += sz
                    fused = jnp.concatenate(parts)
                else:
                    fused = preduce(fused, "proc", op)
                if postscale != 1.0:
                    fused = (fused * postscale).astype(fused.dtype)
                off = 0
                for i in idxs:
                    sz = int(np.prod(shapes[i], dtype=np.int64))
                    outs[i] = jax.lax.dynamic_slice_in_dim(
                        fused, off, sz).reshape(shapes[i])
                    off += sz
            return tuple(outs)

        fn = jax.jit(body)
        self._fn_cache[key] = fn
        return fn

    def ragged_alltoall_supported(self) -> bool:
        """Capability probe: ``lax.ragged_all_to_all`` lowers on TPU but not
        on all platforms (notably XLA:CPU) — compile-check a tiny instance
        once and cache the verdict."""
        if self._ragged_ok is None:
            jax, jnp, P = self._jax, self._jnp, self._P
            try:
                zeros = np.zeros(self.size, np.int32)

                @functools.partial(shard_map, mesh=self._mesh,
                                   in_specs=P("proc"), out_specs=P("proc"),
                                   check_vma=False)
                def probe(x):
                    loc = x[0]
                    return jax.lax.ragged_all_to_all(
                        loc, jnp.zeros_like(loc),
                        jnp.asarray(zeros), jnp.asarray(zeros),
                        jnp.asarray(zeros), jnp.asarray(zeros),
                        axis_name="proc")[None]

                x = jnp.zeros((self.size, 4), jnp.float32)
                jax.jit(probe).lower(x).compile()
                self._ragged_ok = True
            except Exception:
                self._ragged_ok = False
        return self._ragged_ok

    def ragged_alltoall_program(self, pad_send: int, pad_recv: int,
                                trailing, dtype):
        """Device-side uneven alltoall via ``lax.ragged_all_to_all``.

        SPMD requires every participant to run the IDENTICAL program, so
        per-rank split counts must not leak into shapes: operands are
        padded to the global max send/recv totals (host-known from the
        exchanged split table) and the per-rank offset/size vectors travel
        as runtime inputs sharded over 'proc'. Cache key = the bounds, not
        the table — steady-state MoE loads with varying routing reuse one
        executable."""
        key = ("ragged", int(pad_send), int(pad_recv), tuple(trailing),
               str(dtype))
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        jax, jnp, P = self._jax, self._jnp, self._P

        @functools.partial(
            shard_map, mesh=self._mesh,
            in_specs=(P("proc"), P("proc"), P("proc"), P("proc"), P("proc")),
            out_specs=P("proc"), check_vma=False)
        def body(x, in_off, send_sz, out_off, recv_sz):
            loc = x[0]
            out = jnp.zeros((pad_recv,) + tuple(trailing), dtype)
            out = jax.lax.ragged_all_to_all(
                loc, out, in_off[0], send_sz[0], out_off[0], recv_sz[0],
                axis_name="proc")
            return out[None]

        fn = jax.jit(body)
        self._fn_cache[key] = fn
        return fn


class XlaBackend(Backend):
    def __init__(self, state) -> None:
        import jax
        coord = os.environ.get("HVD_TPU_COORD_ADDR", "127.0.0.1")
        base = int(os.environ.get("HVD_TPU_COORD_PORT", "37592"))
        xla_port = int(os.environ.get("HVD_TPU_XLA_COORD_PORT",
                                      str(base + 1)))
        _ensure_jax_distributed(coord, xla_port, state.launched_size,
                                state.launched_rank
                                if state.launched_rank is not None
                                else state.rank)
        super().__init__(jax.process_index(), jax.process_count())
        self._jax = jax
        import jax.numpy as jnp
        self._jnp = jnp
        # one device per process: eager contributions are host arrays, so
        # replicating them over every local chip would just multiply H2D
        # transfers; mesh-mode code paths use the full mesh instead
        nlocal = jax.local_device_count()
        self._proc_devices = \
            np.asarray(jax.devices()).reshape(self.size, nlocal)[:, 0]
        self._group = _XlaGroup(jax, self._proc_devices, self.rank)
        self._disp = _Dispatcher()

    # -- async submission ----------------------------------------------------
    def _submit(self, fn) -> HvdHandle:
        return self._disp.submit(fn)

    def _wrap(self, value, out):
        return self._jnp.asarray(out) if not isinstance(value, np.ndarray) \
            else out

    # -- synchronous bodies (run on the dispatch thread; internal sub-ops
    #    call these directly so a submission never waits on the queue) ------
    def _allreduce(self, group: _XlaGroup, value, op, prescale, postscale):
        arr = _scale(np.asarray(value), prescale)
        garr = group.to_global(arr)
        fn = group.collective("allreduce", op, arr.shape, arr.dtype)
        # AVERAGE / ADASUM are handled inside the collective
        out = _scale(group.local_view(fn(garr)), postscale)
        return self._wrap(value, out)

    def _grouped_allreduce(self, group: _XlaGroup, values, op,
                           prescale, postscale):
        from horovod_tpu.ops.backend import check_scale_dtype
        arrs = [np.asarray(v) for v in values]
        for a in arrs:  # same contract as _scale() on the single path
            check_scale_dtype(a.dtype, prescale)
            check_scale_dtype(a.dtype, postscale)
        shapes = [a.shape for a in arrs]
        dtypes = [a.dtype for a in arrs]
        fn = group.grouped_allreduce_program(op, shapes, dtypes,
                                             prescale, postscale)
        garrs = [group.to_global(a) for a in arrs]
        outs = fn(*garrs)
        return [self._wrap(v, group.local_view(o))
                for v, o in zip(values, outs)]

    def _allgather(self, group: _XlaGroup, name, value):
        arr = np.asarray(value)
        # ragged dim 0: pad to the max (sizes exchanged via an allreduce)
        sizes = np.zeros(group.size, np.int64)
        sizes[group.rank] = arr.shape[0]
        sizes = np.asarray(self._allreduce(
            group, sizes, ReduceOp.SUM, 1.0, 1.0)).astype(np.int64)
        max_rows = int(sizes.max())
        padded = np.zeros((max_rows,) + arr.shape[1:], arr.dtype)
        padded[:arr.shape[0]] = arr
        garr = group.to_global(padded)
        fn = group.collective("allgather", ReduceOp.SUM, padded.shape,
                              padded.dtype)
        full = group.local_view(fn(garr))  # [size*max_rows, ...]
        chunks = [full[i * max_rows:i * max_rows + int(sizes[i])]
                  for i in range(group.size)]
        out = np.concatenate(chunks, axis=0)
        return self._wrap(value, out)

    def _broadcast(self, group: _XlaGroup, value, root_rank):
        if not 0 <= int(root_rank) < group.size:
            raise ValueError(
                f"broadcast root_rank={root_rank} out of range for size "
                f"{group.size}")
        arr = np.asarray(value)
        garr = group.to_global(arr)
        fn = group.collective("broadcast", ReduceOp.SUM, arr.shape,
                              arr.dtype, (int(root_rank),))
        out = group.local_view(fn(garr))
        return self._wrap(value, out)

    def _alltoall(self, group: _XlaGroup, name, value, splits):
        arr = np.asarray(value)
        if splits is None:
            if arr.shape[0] % group.size != 0:
                raise ValueError("alltoall without splits requires dim 0 "
                                 f"divisible by size ({group.size})")
            splits = [arr.shape[0] // group.size] * group.size
        splits = [int(s) for s in splits]
        if len(splits) != group.size:
            raise ValueError("alltoall splits must have one entry per rank")
        if any(s < 0 for s in splits):
            raise ValueError("alltoall splits must be non-negative")
        if sum(splits) != arr.shape[0]:
            raise ValueError(
                f"alltoall splits sum ({sum(splits)}) must equal dim 0 "
                f"({arr.shape[0]})")
        if len(set(splits)) == 1:
            # uniform: single fused XLA all_to_all
            rows = splits[0]
            blocks = arr.reshape((group.size, rows) + arr.shape[1:])
            garr = group.to_global(blocks)
            fn = group.collective("alltoall", ReduceOp.SUM, blocks.shape,
                                  blocks.dtype)
            out = group.local_view(fn(garr)).reshape(
                (group.size * rows,) + arr.shape[1:])
            recv = np.asarray([rows] * group.size, np.int32)
            return self._wrap(value, out), recv

        # uneven: exchange the split table first (host allreduce)
        table = np.zeros((group.size, group.size), np.int64)
        table[group.rank] = splits
        table = np.asarray(self._allreduce(
            group, table, ReduceOp.SUM, 1.0, 1.0))
        recv = table[:, group.rank].astype(np.int32)

        if group.ragged_alltoall_supported():
            # device-side ragged exchange (TPU). One executable for every
            # rank: pad to the table's global max send/recv totals and feed
            # the per-rank offset vectors as sharded runtime inputs.
            n = group.size
            pad_send = int(table.sum(axis=1).max())
            pad_recv = int(table.sum(axis=0).max())
            fn = group.ragged_alltoall_program(pad_send, pad_recv,
                                               arr.shape[1:], arr.dtype)
            padded = np.zeros((pad_send,) + arr.shape[1:], arr.dtype)
            padded[:arr.shape[0]] = arr
            in_off = np.concatenate(
                [[0], np.cumsum(table[group.rank])[:-1]]).astype(np.int32)
            send_sz = table[group.rank].astype(np.int32)
            # out_off[i]: where MY block starts inside receiver i's output
            # (sender-side knowledge of receiver placement; receivers order
            # blocks by source rank)
            out_off = np.asarray(
                [table[:group.rank, dst].sum() for dst in range(n)],
                np.int32)
            recv_sz = table[:, group.rank].astype(np.int32)
            garr = group.to_global(padded)
            out = group.local_view(fn(
                garr, group.to_global(in_off), group.to_global(send_sz),
                group.to_global(out_off), group.to_global(recv_sz)))
            total_recv = int(table[:, group.rank].sum())
            return self._wrap(value, out[:total_recv]), recv

        # portable path: pad each destination block to the global max split
        # and run ONE uniform all_to_all — O(size·max_split) traffic, not
        # the O(size·total) of allgather-everything.
        pad = int(table.max())
        blocks = np.zeros((group.size, pad) + arr.shape[1:], arr.dtype)
        off = 0
        for dst in range(group.size):
            blocks[dst, :splits[dst]] = arr[off:off + splits[dst]]
            off += splits[dst]
        garr = group.to_global(blocks)
        fn = group.collective("alltoall", ReduceOp.SUM, blocks.shape,
                              blocks.dtype)
        full = group.local_view(fn(garr)).reshape(
            (group.size, pad) + arr.shape[1:])
        out = np.concatenate(
            [full[src, :int(table[src, group.rank])]
             for src in range(group.size)], axis=0)
        return self._wrap(value, out), recv

    # -- public async API ----------------------------------------------------
    def allreduce_async(self, name, value, op, prescale=1.0, postscale=1.0):
        return self._submit(lambda: self._allreduce(
            self._group, value, op, prescale, postscale))

    def grouped_allreduce_async(self, names, values, op,
                                prescale=1.0, postscale=1.0):
        return self._submit(lambda: self._grouped_allreduce(
            self._group, list(values), op, prescale, postscale))

    def allgather_async(self, name, value):
        return self._submit(lambda: self._allgather(self._group, name, value))

    def broadcast_async(self, name, value, root_rank):
        return self._submit(lambda: self._broadcast(
            self._group, value, root_rank))

    def alltoall_async(self, name, value, splits=None):
        return self._submit(lambda: self._alltoall(
            self._group, name, value, splits))

    def barrier(self) -> None:
        self._submit(lambda: self._allreduce(
            self._group, np.zeros(1, np.float32), ReduceOp.SUM,
            1.0, 1.0)).wait()

    def join(self, device: int = -1) -> int:
        raise NotImplementedError(
            "hvd.join() requires dynamic negotiation (ranks submit different "
            "collective sequences by definition), which the same-order XLA "
            "eager data plane cannot provide; use the TCP core backend "
            "(unset HOROVOD_TPU_OPERATIONS) for join-style uneven data, or "
            "pad batches so every rank runs the same steps")

    def make_subset(self, ranks: Sequence[int]):
        """Per-set sub-mesh + program cache (reference: per-set NCCL comms,
        ``nccl_operations.cc:65-107``). Shares this backend's dispatch
        thread so each process keeps ONE total submission order."""
        ranks = sorted(set(int(r) for r in ranks))
        if any(not 0 <= r < self.size for r in ranks):
            raise ValueError(f"process-set ranks {ranks} out of range for "
                             f"world size {self.size}")
        return _XlaSubsetBackend(self, ranks)

    def shutdown(self) -> None:
        self._disp.shutdown()
        # jax.distributed teardown happens at process exit


class _XlaSubsetBackend(Backend):
    """Process-set view over the parent XLA backend: same dispatch thread,
    own sub-mesh and compiled-program cache. Non-members hold a handle whose
    collectives raise (reference: non-member submissions are rejected,
    ``process_set.h:26-81``)."""

    def __init__(self, parent: XlaBackend, ranks: List[int]) -> None:
        self._parent = parent
        self._ranks = ranks
        my = parent.rank
        set_rank = ranks.index(my) if my in ranks else -1
        super().__init__(set_rank, len(ranks))
        self._group = None
        if set_rank >= 0:
            devices = parent._proc_devices[ranks]
            self._group = _XlaGroup(parent._jax, devices, set_rank)

    def _require_member(self) -> _XlaGroup:
        if self._group is None:
            raise RuntimeError(
                f"process {self._parent.rank} is not a member of process set "
                f"{self._ranks} and cannot submit collectives to it")
        return self._group

    def join(self, device: int = -1) -> int:
        # same-order data plane: join is as impossible per-set as globally
        return self._parent.join(device)

    def allreduce_async(self, name, value, op, prescale=1.0, postscale=1.0):
        g = self._require_member()
        return self._parent._submit(lambda: self._parent._allreduce(
            g, value, op, prescale, postscale))

    def grouped_allreduce_async(self, names, values, op,
                                prescale=1.0, postscale=1.0):
        g = self._require_member()
        return self._parent._submit(lambda: self._parent._grouped_allreduce(
            g, list(values), op, prescale, postscale))

    def allgather_async(self, name, value):
        g = self._require_member()
        return self._parent._submit(lambda: self._parent._allgather(
            g, name, value))

    def broadcast_async(self, name, value, root_rank):
        """``root_rank`` is the GLOBAL rank (reference semantics,
        ``core_backend.broadcast_async`` does the same translation)."""
        g = self._require_member()
        if int(root_rank) in self._ranks:
            set_root = self._ranks.index(int(root_rank))
        else:
            raise ValueError(
                f"broadcast root_rank={root_rank} is not a member of "
                f"process set {self._ranks}")
        return self._parent._submit(lambda: self._parent._broadcast(
            g, value, set_root))

    def alltoall_async(self, name, value, splits=None):
        g = self._require_member()
        return self._parent._submit(lambda: self._parent._alltoall(
            g, name, value, splits))

    def barrier(self) -> None:
        g = self._require_member()
        self._parent._submit(lambda: self._parent._allreduce(
            g, np.zeros(1, np.float32), ReduceOp.SUM, 1.0, 1.0)).wait()

    def make_subset(self, ranks: Sequence[int]):
        raise NotImplementedError(
            "nested process sets are not supported; create sets from the "
            "global backend (matches the reference, which registers all "
            "sets against the global table)")

    def shutdown(self) -> None:
        pass  # the dispatch thread belongs to the parent
