"""XLA data-plane backend: eager multi-process collectives executed as
jitted XLA collectives over the global mesh (ICI within a slice, DCN/gloo
across hosts).

This is the reference's NCCL role (SURVEY.md §2.7: "NCCL → ICI collectives
via jitted XLA ops over the pod slice") for the EAGER path: per-process
arrays become shards of a global array and one cached jitted shard_map
program moves the bytes — no host round-trip through the TCP rings.

Contract: every member process must issue the same collectives in the same
order (the standard data-parallel training pattern, and exactly what the
reference's response cache converges to in steady state). For dynamically
ordered submissions use the TCP core backend, which negotiates ordering.
Select with ``HVD_TPU_OPERATIONS=XLA_EAGER`` (reference knob analog:
``HOROVOD_CPU_OPERATIONS``/compile-time ``HOROVOD_GPU_ALLREDUCE``).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Optional, Sequence

import numpy as np

from horovod_tpu.ops.backend import Backend, HvdHandle
from horovod_tpu.ops.reduce_op import ReduceOp

_DIST_LOCK = threading.Lock()
_DIST_INITIALIZED = False


def _ensure_jax_distributed(coord_addr: str, port: int, size: int,
                            rank: int) -> None:
    global _DIST_INITIALIZED
    with _DIST_LOCK:
        if _DIST_INITIALIZED:
            return
        import jax
        jax.distributed.initialize(
            coordinator_address=f"{coord_addr}:{port}",
            num_processes=size, process_id=rank)
        _DIST_INITIALIZED = True


class XlaBackend(Backend):
    def __init__(self, state) -> None:
        import jax
        coord = os.environ.get("HVD_TPU_COORD_ADDR", "127.0.0.1")
        base = int(os.environ.get("HVD_TPU_COORD_PORT", "37592"))
        xla_port = int(os.environ.get("HVD_TPU_XLA_COORD_PORT",
                                      str(base + 1)))
        _ensure_jax_distributed(coord, xla_port, state.launched_size,
                                state.launched_rank
                                if state.launched_rank is not None
                                else state.rank)
        super().__init__(jax.process_index(), jax.process_count())
        self._jax = jax
        import jax.numpy as jnp
        self._jnp = jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        self._P = P
        self._NS = NamedSharding
        # one device per process: eager contributions are host arrays, so
        # replicating them over every local chip would just multiply H2D
        # transfers; mesh-mode code paths use the full mesh instead
        nlocal = jax.local_device_count()
        devs = np.asarray(jax.devices()).reshape(self.size, nlocal)[:, 0]
        self._mesh = Mesh(devs, ("proc",))
        self._fn_cache = {}

    # -- helpers -------------------------------------------------------------
    def _to_global(self, arr: np.ndarray):
        """Per-process contribution → global array [size, ...] sharded over
        'proc' (replicated over local devices)."""
        jax = self._jax
        sharding = self._NS(self._mesh, self._P("proc"))
        row = np.asarray(arr)[None]
        my_dev = self._mesh.devices[self.rank]
        shards = [jax.device_put(row, my_dev)]
        return jax.make_array_from_single_device_arrays(
            (self.size,) + np.asarray(arr).shape, sharding, shards)

    def _local_view(self, garr) -> np.ndarray:
        return np.asarray(garr.addressable_shards[0].data)

    def _collective(self, kind: str, op: ReduceOp, shape, dtype, extra=()):
        key = (kind, op, tuple(shape), str(dtype), tuple(extra))
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        jax, jnp, P = self._jax, self._jnp, self._P
        mesh = self._mesh
        from horovod_tpu.ops.mesh_collectives import preduce

        if kind == "allreduce":
            @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P(), check_vma=False)
            def body(x):
                return preduce(x[0], "proc", op)
        elif kind == "allgather":
            @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P(), check_vma=False)
            def body(x):
                return jax.lax.all_gather(x[0], "proc", axis=0, tiled=True)
        elif kind == "broadcast":
            (root,) = extra

            @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P(), check_vma=False)
            def body(x):
                idx = jax.lax.axis_index("proc")
                masked = jnp.where(idx == root, x[0],
                                   jnp.zeros_like(x[0]))
                return jax.lax.psum(masked, "proc")
        elif kind == "alltoall":
            @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("proc"),
                               out_specs=P("proc"), check_vma=False)
            def body(x):
                return jax.lax.all_to_all(x, "proc", split_axis=1,
                                          concat_axis=0, tiled=False)
        else:
            raise ValueError(kind)
        fn = jax.jit(body)
        self._fn_cache[key] = fn
        return fn

    # -- collectives ---------------------------------------------------------
    def allreduce_async(self, name, value, op, prescale=1.0, postscale=1.0):
        from horovod_tpu.ops.backend import _scale
        arr = _scale(np.asarray(value), prescale)
        garr = self._to_global(arr)
        fn = self._collective("allreduce", op, arr.shape, arr.dtype)
        # AVERAGE is handled inside the collective (pmean)
        out = _scale(self._local_view(fn(garr)), postscale)
        result = self._jnp.asarray(out) if not isinstance(value, np.ndarray) \
            else out
        return HvdHandle.done(result)

    def grouped_allreduce_async(self, names, values, op,
                                prescale=1.0, postscale=1.0):
        outs = [self.allreduce_async(n, v, op, prescale, postscale).wait()
                for n, v in zip(names, values)]
        return HvdHandle.done(outs)

    def allgather_async(self, name, value):
        arr = np.asarray(value)
        # ragged dim 0: pad to the max (sizes exchanged via an allreduce)
        sizes = np.zeros(self.size, np.int64)
        sizes[self.rank] = arr.shape[0]
        sizes = np.asarray(self.allreduce_async(
            f"{name}.sizes", sizes, ReduceOp.SUM).wait()).astype(np.int64)
        max_rows = int(sizes.max())
        padded = np.zeros((max_rows,) + arr.shape[1:], arr.dtype)
        padded[:arr.shape[0]] = arr
        garr = self._to_global(padded)
        fn = self._collective("allgather", ReduceOp.SUM, padded.shape,
                              padded.dtype)
        full = self._local_view(fn(garr))  # [size*max_rows, ...]
        chunks = [full[i * max_rows:i * max_rows + int(sizes[i])]
                  for i in range(self.size)]
        out = np.concatenate(chunks, axis=0)
        result = self._jnp.asarray(out) if not isinstance(value, np.ndarray) \
            else out
        return HvdHandle.done(result)

    def broadcast_async(self, name, value, root_rank):
        if not 0 <= int(root_rank) < self.size:
            raise ValueError(
                f"broadcast root_rank={root_rank} out of range for size "
                f"{self.size}")
        arr = np.asarray(value)
        garr = self._to_global(arr)
        fn = self._collective("broadcast", ReduceOp.SUM, arr.shape,
                              arr.dtype, (int(root_rank),))
        out = self._local_view(fn(garr))
        result = self._jnp.asarray(out) if not isinstance(value, np.ndarray) \
            else out
        return HvdHandle.done(result)

    def alltoall_async(self, name, value, splits=None):
        arr = np.asarray(value)
        if splits is None:
            if arr.shape[0] % self.size != 0:
                raise ValueError("alltoall without splits requires dim 0 "
                                 f"divisible by size ({self.size})")
            splits = [arr.shape[0] // self.size] * self.size
        splits = [int(s) for s in splits]
        if len(splits) != self.size:
            raise ValueError("alltoall splits must have one entry per rank")
        if any(s < 0 for s in splits):
            raise ValueError("alltoall splits must be non-negative")
        if sum(splits) != arr.shape[0]:
            raise ValueError(
                f"alltoall splits sum ({sum(splits)}) must equal dim 0 "
                f"({arr.shape[0]})")
        if len(set(splits)) == 1:
            # uniform: single fused XLA all_to_all
            rows = splits[0]
            blocks = arr.reshape((self.size, rows) + arr.shape[1:])
            garr = self._to_global(blocks)
            fn = self._collective("alltoall", ReduceOp.SUM, blocks.shape,
                                  blocks.dtype)
            out = self._local_view(fn(garr)).reshape(
                (self.size * rows,) + arr.shape[1:])
            recv = np.asarray([rows] * self.size, np.int32)
        else:
            # uneven: exchange split tables, then allgather + slice (the
            # correctness path; ragged_all_to_all is a future optimization)
            table = np.zeros((self.size, self.size), np.int64)
            table[self.rank] = splits
            table = np.asarray(self.allreduce_async(
                f"{name}.splits", table, ReduceOp.SUM).wait())
            gathered = np.asarray(self.allgather_async(
                f"{name}.data", arr).wait())
            row_offsets = np.concatenate(
                [[0], np.cumsum(table.sum(1))])[:-1]
            pieces = []
            recv = []
            for src in range(self.size):
                start = row_offsets[src] + table[src, :self.rank].sum()
                n = table[src, self.rank]
                pieces.append(gathered[int(start):int(start + n)])
                recv.append(int(n))
            out = np.concatenate(pieces, axis=0)
            recv = np.asarray(recv, np.int32)
        result = self._jnp.asarray(out) if not isinstance(value, np.ndarray) \
            else out
        return HvdHandle.done((result, recv))

    def barrier(self) -> None:
        self.allreduce_async("__barrier__", np.zeros(1, np.float32),
                             ReduceOp.SUM).wait()

    def make_subset(self, ranks: Sequence[int]):
        raise NotImplementedError(
            "process sets over the XLA eager backend are not supported yet; "
            "use the TCP core backend (unset HVD_TPU_OPERATIONS) for "
            "process-set workloads")

    def shutdown(self) -> None:
        pass  # jax.distributed teardown happens at process exit
