"""Block-wise int8 quantize/dequantize as Pallas TPU kernels.

The hot path of a quantized gradient exchange is the codec itself: for a
gradient of N floats the quantizer reads N floats and writes N bytes +
N/block scales, and the dequantizer does the reverse — both pure
streaming passes that XLA happily splits into several HBM sweeps
(abs, max-reduce, divide, round, cast). Each kernel here does its whole
block's work in one VMEM round trip: a [rows, block] tile is read once,
the per-row absmax/scale is computed in registers, and the int8 payload
plus the fp32 scale column are written back — one read, two writes,
nothing rematerialized.

Layout contract (same convention as :mod:`ops.pallas_xent`): operands
are 2-D ``[n_blocks, block]`` with ``block`` on the lane dimension
(multiple of 128) and blocks tiled ``ROWS`` at a time on the sublane
dimension (32, the int8 sublane tile). Scales ride as ``[n_blocks, 1]``.

A pure-XLA fallback with the same semantics (round-half-to-even, same
zero-block guard) runs on CPU or when shapes defeat the tiling; scales
agree with the kernel to 1 ULP of the ``absmax/127`` division, payloads
to ±1 code. ``interpret=True`` exercises the kernel itself off-TPU
(tier-1 CI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# int8 native tile is (32, 128): 32 blocks per grid step, lane dim must
# be a 128-multiple for the kernel to engage.
ROWS = 32


def _quantize_kernel(x_ref, vals_ref, scales_ref):
    """One [ROWS, block] tile: per-row absmax -> scale -> rounded int8."""
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    # all-zero (or padding) blocks quantize through scale 1 -> zeros
    scale = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
    scales_ref[:] = scale
    vals_ref[:] = jnp.clip(jnp.round(x / scale), -127.0, 127.0
                           ).astype(jnp.int8)


def _dequantize_kernel(vals_ref, scales_ref, out_ref):
    out_ref[:] = vals_ref[...].astype(jnp.float32) * scales_ref[...]


def _xla_quantize(blocks):
    """Fallback with the SAME semantics as the kernel (jnp.round is
    round-half-to-even on both paths; scales agree to 1 ULP)."""
    x = blocks.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
    vals = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    return vals, scale


def _xla_dequantize(vals, scales):
    return vals.astype(jnp.float32) * scales


def _kernel_ok(n_blocks: int, block: int, interpret: bool) -> bool:
    on_tpu = jax.default_backend() == "tpu"
    return (on_tpu or interpret) and block % 128 == 0 and n_blocks > 0


def block_quantize(blocks: jax.Array, interpret: bool = False):
    """``[n_blocks, block]`` floats -> ``(int8 values [n_blocks, block],
    fp32 scales [n_blocks, 1])`` with per-block scale ``absmax/127``.

    Engages the fused kernel on TPU (or under ``interpret=True``
    anywhere); other backends and non-128-multiple blocks take the
    numerically identical XLA path. Rows are padded to the 32-row int8
    tile internally and stripped on return.
    """
    n_blocks, block = blocks.shape
    if not _kernel_ok(n_blocks, block, interpret):
        return _xla_quantize(blocks)
    pad = (-n_blocks) % ROWS
    if pad:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((pad, block), blocks.dtype)], axis=0)
    n = n_blocks + pad
    vals, scales = pl.pallas_call(
        _quantize_kernel,
        grid=(n // ROWS,),
        in_specs=[pl.BlockSpec((ROWS, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, block), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(blocks)
    if pad:
        vals, scales = vals[:n_blocks], scales[:n_blocks]
    return vals, scales


def _quantize_ef_kernel(x_ref, vals_ref, scales_ref, res_ref):
    """Quantize + error-feedback residual in ONE pass: the residual
    (``x − codes·scale``) is what a separate dequantize would have to
    re-read the whole payload to compute — here it falls out of the
    registers that just produced the codes."""
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
    scales_ref[:] = scale
    q = jnp.clip(jnp.round(x / scale), -127.0, 127.0)
    vals_ref[:] = q.astype(jnp.int8)
    res_ref[:] = x - q * scale


def _xla_quantize_ef(blocks):
    x = blocks.astype(jnp.float32)
    vals, scale = _xla_quantize(blocks)
    return vals, scale, x - vals.astype(jnp.float32) * scale


def block_quantize_ef(blocks: jax.Array, interpret: bool = False):
    """``[n_blocks, block]`` floats -> ``(int8 values, fp32 scales
    [n_blocks, 1], fp32 residual [n_blocks, block])`` where ``residual =
    blocks − dequantize(values, scales)`` — the error-feedback carry,
    produced in the same VMEM round trip as the codes instead of by a
    second dequantize sweep (:mod:`train.fused_apply`)."""
    n_blocks, block = blocks.shape
    if not _kernel_ok(n_blocks, block, interpret):
        return _xla_quantize_ef(blocks)
    pad = (-n_blocks) % ROWS
    if pad:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((pad, block), blocks.dtype)], axis=0)
    n = n_blocks + pad
    vals, scales, res = pl.pallas_call(
        _quantize_ef_kernel,
        grid=(n // ROWS,),
        in_specs=[pl.BlockSpec((ROWS, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, block), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, block), jnp.float32),
        ],
        interpret=interpret,
    )(blocks)
    if pad:
        vals, scales, res = vals[:n_blocks], scales[:n_blocks], \
            res[:n_blocks]
    return vals, scales, res


def block_dequantize(vals: jax.Array, scales: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """Inverse of :func:`block_quantize`: ``values * scale`` per block,
    returned as float32 ``[n_blocks, block]``."""
    n_blocks, block = vals.shape
    if not _kernel_ok(n_blocks, block, interpret):
        return _xla_dequantize(vals, scales)
    pad = (-n_blocks) % ROWS
    if pad:
        vals = jnp.concatenate(
            [vals, jnp.zeros((pad, block), vals.dtype)], axis=0)
        scales = jnp.concatenate(
            [scales, jnp.ones((pad, 1), scales.dtype)], axis=0)
    n = n_blocks + pad
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(n // ROWS,),
        in_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, block), jnp.float32),
        interpret=interpret,
    )(vals, scales)
    return out[:n_blocks] if pad else out


# ---------------------------------------------------------------------------
# Fused dequantize + optimizer apply (docs/PERF.md "Overlap & bucketing")
#
# After a quantized gradient exchange the tail used to be three separate
# HBM sweeps: dequantize codes -> fp32 gradient, momentum update, delta.
# Each kernel below reads the int8 codes + scales + the optimizer
# moments ONCE, does the whole dequantize->moment->delta chain in
# registers, and writes the delta + new moments back — one VMEM round
# trip for the entire optimizer tail. Scalar hyperparameters ride in
# SMEM. The delta is optax-convention (``params += delta``), so the
# caller's ``optax.apply_updates`` add fuses with the surrounding graph.
# ---------------------------------------------------------------------------


def _fused_sgd0_kernel(h_ref, vals_ref, scales_ref, delta_ref):
    # h = [lr]
    g = vals_ref[...].astype(jnp.float32) * scales_ref[...]
    delta_ref[:] = -h_ref[0] * g


def _fused_sgd_kernel(h_ref, vals_ref, scales_ref, mom_ref,
                      delta_ref, nmom_ref):
    # h = [lr, momentum]; optax.sgd trace: t = g + mu*t_prev
    g = vals_ref[...].astype(jnp.float32) * scales_ref[...]
    m = g + h_ref[1] * mom_ref[...]
    nmom_ref[:] = m
    delta_ref[:] = -h_ref[0] * m


def _fused_adam_kernel(h_ref, vals_ref, scales_ref, m_ref, v_ref,
                       delta_ref, nm_ref, nv_ref):
    # h = [lr, b1, b2, eps, bc1, bc2] with bcK = 1 - bK**t (optax
    # bias_correction at count t, computed by the caller)
    g = vals_ref[...].astype(jnp.float32) * scales_ref[...]
    b1, b2 = h_ref[1], h_ref[2]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    nm_ref[:] = m
    nv_ref[:] = v
    delta_ref[:] = -h_ref[0] * (m / h_ref[4]) / \
        (jnp.sqrt(v / h_ref[5]) + h_ref[3])


def _xla_fused_sgd(h, vals, scales, mom):
    g = vals.astype(jnp.float32) * scales
    if mom is None:
        return -h[0] * g, None
    m = g + h[1] * mom
    return -h[0] * m, m


def _xla_fused_adam(h, vals, scales, m, v):
    g = vals.astype(jnp.float32) * scales
    m = h[1] * m + (1.0 - h[1]) * g
    v = h[2] * v + (1.0 - h[2]) * g * g
    delta = -h[0] * (m / h[4]) / (jnp.sqrt(v / h[5]) + h[3])
    return delta, m, v


def _pad_rows(x, pad, fill=0.0):
    return jnp.concatenate(
        [x, jnp.full((pad,) + x.shape[1:], fill, x.dtype)], axis=0)


def fused_sgd_apply(vals: jax.Array, scales: jax.Array, mom, lr, momentum,
                    interpret: bool = False):
    """int8 codes + scales (+ momentum blocks) -> ``(delta, new_mom)``:
    dequantize and the optax ``sgd(lr, momentum)`` update in one fused
    pass. ``mom=None`` selects the momentum-free variant (``new_mom`` is
    None). ``lr``/``momentum`` may be traced scalars."""
    n_blocks, block = vals.shape
    if not _kernel_ok(n_blocks, block, interpret):
        h = jnp.stack([jnp.float32(lr), jnp.float32(momentum)])
        return _xla_fused_sgd(h, vals, scales, mom)
    from jax.experimental.pallas import tpu as pltpu
    pad = (-n_blocks) % ROWS
    if pad:
        vals = _pad_rows(vals, pad)
        scales = _pad_rows(scales, pad, 1.0)
        if mom is not None:
            mom = _pad_rows(mom, pad)
    n = n_blocks + pad
    tile = lambda r: pl.BlockSpec((ROWS, r), lambda i: (i, 0))  # noqa: E731
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    if mom is None:
        h = jnp.stack([jnp.float32(lr)])
        delta = pl.pallas_call(
            _fused_sgd0_kernel,
            grid=(n // ROWS,),
            in_specs=[smem, tile(block), tile(1)],
            out_specs=tile(block),
            out_shape=jax.ShapeDtypeStruct((n, block), jnp.float32),
            interpret=interpret,
        )(h, vals, scales)
        new_mom = None
    else:
        h = jnp.stack([jnp.float32(lr), jnp.float32(momentum)])
        delta, new_mom = pl.pallas_call(
            _fused_sgd_kernel,
            grid=(n // ROWS,),
            in_specs=[smem, tile(block), tile(1), tile(block)],
            out_specs=[tile(block), tile(block)],
            out_shape=[jax.ShapeDtypeStruct((n, block), jnp.float32),
                       jax.ShapeDtypeStruct((n, block), jnp.float32)],
            interpret=interpret,
        )(h, vals, scales, mom)
        new_mom = new_mom[:n_blocks] if pad else new_mom
    return (delta[:n_blocks] if pad else delta), new_mom


def fused_adam_apply(vals: jax.Array, scales: jax.Array, m: jax.Array,
                     v: jax.Array, lr, b1, b2, eps, bc1, bc2,
                     interpret: bool = False):
    """int8 codes + scales + Adam moments -> ``(delta, new_m, new_v)``
    with optax.adam numerics (``bc1``/``bc2`` are the caller-computed
    ``1 − βₖᵗ`` bias corrections — traced scalars are fine)."""
    n_blocks, block = vals.shape
    h = jnp.stack([jnp.float32(lr), jnp.float32(b1), jnp.float32(b2),
                   jnp.float32(eps), jnp.float32(bc1), jnp.float32(bc2)])
    if not _kernel_ok(n_blocks, block, interpret):
        return _xla_fused_adam(h, vals, scales, m, v)
    from jax.experimental.pallas import tpu as pltpu
    pad = (-n_blocks) % ROWS
    if pad:
        vals = _pad_rows(vals, pad)
        scales = _pad_rows(scales, pad, 1.0)
        m = _pad_rows(m, pad)
        v = _pad_rows(v, pad)
    n = n_blocks + pad
    tile = lambda r: pl.BlockSpec((ROWS, r), lambda i: (i, 0))  # noqa: E731
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    delta, nm, nv = pl.pallas_call(
        _fused_adam_kernel,
        grid=(n // ROWS,),
        in_specs=[smem, tile(block), tile(1), tile(block), tile(block)],
        out_specs=[tile(block), tile(block), tile(block)],
        out_shape=[jax.ShapeDtypeStruct((n, block), jnp.float32)] * 3,
        interpret=interpret,
    )(h, vals, scales, m, v)
    if pad:
        delta, nm, nv = delta[:n_blocks], nm[:n_blocks], nv[:n_blocks]
    return delta, nm, nv
