"""Block-wise int8 quantize/dequantize as Pallas TPU kernels.

The hot path of a quantized gradient exchange is the codec itself: for a
gradient of N floats the quantizer reads N floats and writes N bytes +
N/block scales, and the dequantizer does the reverse — both pure
streaming passes that XLA happily splits into several HBM sweeps
(abs, max-reduce, divide, round, cast). Each kernel here does its whole
block's work in one VMEM round trip: a [rows, block] tile is read once,
the per-row absmax/scale is computed in registers, and the int8 payload
plus the fp32 scale column are written back — one read, two writes,
nothing rematerialized.

Layout contract (same convention as :mod:`ops.pallas_xent`): operands
are 2-D ``[n_blocks, block]`` with ``block`` on the lane dimension
(multiple of 128) and blocks tiled ``ROWS`` at a time on the sublane
dimension (32, the int8 sublane tile). Scales ride as ``[n_blocks, 1]``.

A pure-XLA fallback with the same semantics (round-half-to-even, same
zero-block guard) runs on CPU or when shapes defeat the tiling; scales
agree with the kernel to 1 ULP of the ``absmax/127`` division, payloads
to ±1 code. ``interpret=True`` exercises the kernel itself off-TPU
(tier-1 CI).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# int8 native tile is (32, 128): 32 blocks per grid step, lane dim must
# be a 128-multiple for the kernel to engage.
ROWS = 32


def _quantize_kernel(x_ref, vals_ref, scales_ref):
    """One [ROWS, block] tile: per-row absmax -> scale -> rounded int8."""
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    # all-zero (or padding) blocks quantize through scale 1 -> zeros
    scale = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
    scales_ref[:] = scale
    vals_ref[:] = jnp.clip(jnp.round(x / scale), -127.0, 127.0
                           ).astype(jnp.int8)


def _dequantize_kernel(vals_ref, scales_ref, out_ref):
    out_ref[:] = vals_ref[...].astype(jnp.float32) * scales_ref[...]


def _xla_quantize(blocks):
    """Fallback with the SAME semantics as the kernel (jnp.round is
    round-half-to-even on both paths; scales agree to 1 ULP)."""
    x = blocks.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0.0, absmax / 127.0, 1.0)
    vals = jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)
    return vals, scale


def _xla_dequantize(vals, scales):
    return vals.astype(jnp.float32) * scales


def _kernel_ok(n_blocks: int, block: int, interpret: bool) -> bool:
    on_tpu = jax.default_backend() == "tpu"
    return (on_tpu or interpret) and block % 128 == 0 and n_blocks > 0


def block_quantize(blocks: jax.Array, interpret: bool = False):
    """``[n_blocks, block]`` floats -> ``(int8 values [n_blocks, block],
    fp32 scales [n_blocks, 1])`` with per-block scale ``absmax/127``.

    Engages the fused kernel on TPU (or under ``interpret=True``
    anywhere); other backends and non-128-multiple blocks take the
    numerically identical XLA path. Rows are padded to the 32-row int8
    tile internally and stripped on return.
    """
    n_blocks, block = blocks.shape
    if not _kernel_ok(n_blocks, block, interpret):
        return _xla_quantize(blocks)
    pad = (-n_blocks) % ROWS
    if pad:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((pad, block), blocks.dtype)], axis=0)
    n = n_blocks + pad
    vals, scales = pl.pallas_call(
        _quantize_kernel,
        grid=(n // ROWS,),
        in_specs=[pl.BlockSpec((ROWS, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, block), jnp.int8),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(blocks)
    if pad:
        vals, scales = vals[:n_blocks], scales[:n_blocks]
    return vals, scales


def block_dequantize(vals: jax.Array, scales: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """Inverse of :func:`block_quantize`: ``values * scale`` per block,
    returned as float32 ``[n_blocks, block]``."""
    n_blocks, block = vals.shape
    if not _kernel_ok(n_blocks, block, interpret):
        return _xla_dequantize(vals, scales)
    pad = (-n_blocks) % ROWS
    if pad:
        vals = jnp.concatenate(
            [vals, jnp.zeros((pad, block), vals.dtype)], axis=0)
        scales = jnp.concatenate(
            [scales, jnp.ones((pad, 1), scales.dtype)], axis=0)
    n = n_blocks + pad
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=(n // ROWS,),
        in_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, block), jnp.float32),
        interpret=interpret,
    )(vals, scales)
    return out[:n_blocks] if pad else out
