"""Adasum — adaptive summation that preserves convergence when scaling
batch size (reference: ``horovod/common/ops/adasum/adasum.h:38-180``, the
VHDD recursive vector-halving distance-doubling algorithm, and
``_DistributedAdasumOptimizer``, ``horovod/torch/optimizer.py:335``).

TPU-native formulation: instead of VHDD message passing, the pairwise
combine

    a' = (1 - dot(a,b) / (2*||a||^2)) * a  +  (1 - dot(a,b) / (2*||b||^2)) * b

is applied in a binary-tree fold over contributions gathered with one XLA
``all_gather`` (ICI bandwidth makes the gather cheap; the tree fold is pure
VPU work that XLA fuses). The result is bit-identical in structure to the
reference's recursion: level k combines partners at distance 2**k.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu.common.basics import size
from horovod_tpu.common.process_sets import ProcessSet, global_process_set


def adasum_combine(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise Adasum (reference: ``ComputeDotAndNormSqrds`` +
    ``ScaledAdd`` fused loop, ``adasum/adasum.h:312-564``)."""
    af = a.astype(jnp.float32).reshape(-1)
    bf = b.astype(jnp.float32).reshape(-1)
    dot = jnp.vdot(af, bf)
    na = jnp.vdot(af, af)
    nb = jnp.vdot(bf, bf)
    # Guard zero norms (reference guards with if-nonzero before dividing).
    ca = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)), 1.0)
    cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)), 1.0)
    out = ca * af + cb * bf
    return out.reshape(a.shape).astype(a.dtype)


def adasum_tree_reduce(stacked: jax.Array) -> jax.Array:
    """Fold ``stacked[n, ...]`` contributions with the Adasum combine in a
    binary tree, matching VHDD's level structure (distance 1, 2, 4, ...).

    Non-power-of-two ``n`` is handled by zero-padding: ``combine(a, 0) == a``
    (dot = 0 and the zero-norm guard gives coefficients 1), so padding is
    exact — the reference handles ragged counts analogously by pairing the
    overflow ranks before the power-of-two recursion (``adasum.h:205-240``).
    """
    n = stacked.shape[0]
    if n & (n - 1) != 0:
        from horovod_tpu.common.util import next_power_of_two
        pad = next_power_of_two(n) - n
        stacked = jnp.concatenate(
            [stacked, jnp.zeros((pad,) + stacked.shape[1:], stacked.dtype)])
        n = stacked.shape[0]
    while n > 1:
        half = n // 2
        a = stacked[0::2][:half]
        b = stacked[1::2][:half]
        stacked = jax.vmap(adasum_combine)(a, b)
        n = half
    return stacked[0]


def adasum_allreduce_along(x: jax.Array, axis_name: str) -> jax.Array:
    """SPMD Adasum over a named mesh axis (use inside shard_map)."""
    gathered = jax.lax.all_gather(x, axis_name)  # [axis_size, ...]
    return adasum_tree_reduce(gathered)


def AdasumGradTransform(process_set: ProcessSet = global_process_set,
                        axis_name: Optional[str] = None
                        ) -> optax.GradientTransformation:
    """optax transform applying Adasum across workers (used by
    ``DistributedOptimizer(op=hvd.Adasum)``)."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        from horovod_tpu.common.util import is_traced
        traced = is_traced(updates)
        if traced and axis_name is not None:
            new = jax.tree_util.tree_map(
                lambda g: adasum_allreduce_along(g, axis_name), updates)
        elif not traced and size() > 1:
            from horovod_tpu.ops import collectives as C
            def one(i, g):
                stacked = C.allgather(jnp.asarray(g)[None, ...],
                                      name=f"adasum.{i}",
                                      process_set=process_set)
                return adasum_tree_reduce(jnp.asarray(stacked))
            leaves, treedef = jax.tree_util.tree_flatten(updates)
            new = jax.tree_util.tree_unflatten(
                treedef, [one(i, g) for i, g in enumerate(leaves)])
        else:
            new = updates  # single contributor: Adasum(a) = a
        return new, state

    return optax.GradientTransformation(init_fn, update_fn)
