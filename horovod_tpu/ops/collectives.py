"""Public eager collective API on ``jax.Array`` / numpy.

Mirrors the reference's per-framework op surface
(``horovod/torch/mpi_ops.py:143-903``, ``horovod/tensorflow/mpi_ops.py:108-356``):
sync and async variants of allreduce / grouped_allreduce / allgather /
broadcast / alltoall, plus ``poll``/``synchronize``/``join``/``barrier``.

Semantics notes vs the reference:

* ``op=Average`` divides by the process-set size (reference: AVERAGE →
  postscale 1/size, ``operations.cc:1342-1500``).
* Gradient flow: the JAX-idiomatic counterpart of torch autograd hooks /
  ``tf.RegisterGradient`` is :func:`horovod_tpu.DistributedGradTransform`
  (gradient averaging inside the optimizer transform) — these eager functions
  operate on concrete arrays outside of traced code.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from horovod_tpu.common.basics import _require_init
from horovod_tpu.common.process_sets import ProcessSet, global_process_set
from horovod_tpu.diagnostics import spans as _spans
from horovod_tpu.diagnostics.flight_recorder import record_event
from horovod_tpu.metrics.registry import default_registry
from horovod_tpu.ops.backend import Backend, HvdHandle, check_scale_dtype
from horovod_tpu.ops.reduce_op import Adasum, Average, ReduceOp, Sum


_CALL_COUNTERS: dict = {}


def _trace_enqueue(kind: str, names) -> list:
    """Diagnostics seam for every eager enqueue: allocate the
    per-collective span id(s) (``diagnostics.spans`` — deterministic
    across ranks, the cross-rank correlation key), flight-record the
    enqueue, open the per-rank timeline spans, and stamp the span into
    the C++ engine trace when it is live (``hvd_timeline_mark``).
    Returns ``[(name, span), ...]``."""
    st = _require_init()
    if isinstance(names, str):
        names = [names]
    if not names:
        return []
    pairs = [(name, _spans.next_span(name)) for name in names]
    tl = st.timeline
    if tl is not None and tl.enabled:
        for name, span in pairs:
            tl.collective_begin(name, kind, span)
    mark = getattr(st.backend, "timeline_mark", None)
    if mark is not None and st.backend.core_timeline_enabled():
        for name, span in pairs:
            mark(f"enqueue_{kind}", span)
    record_event("enqueue", op=kind, name=pairs[0][0], n=len(pairs),
                 span=pairs[0][1])
    return pairs


def _trace_done(handle: HvdHandle, kind: str, pairs) -> HvdHandle:
    """Flight-record completion (and close the timeline spans) when the
    handle resolves. Observability only — never raises into the wait."""
    if not pairs:
        return handle
    st = _require_init()
    tl = st.timeline

    def on_done(ok: bool) -> None:
        record_event("complete", op=kind, name=pairs[0][0],
                     span=pairs[0][1], ok=ok)
        if tl is not None and tl.enabled:
            for name, span in pairs:
                tl.collective_end(name, span, ok=ok)

    handle.add_done_callback(on_done)
    return handle


def _count_call(kind: str) -> None:
    """Per-kind eager-API call counter (``docs/OBSERVABILITY.md``): the
    registry-side complement of the engine's submitted/executed counters —
    visible on ``/metrics`` even for backends without native counters.
    The Counter is resolved once per kind: the submission hot path pays
    one dict hit + the counter's own lock, not a registry lookup."""
    counter = _CALL_COUNTERS.get(kind)
    if counter is None:
        counter = _CALL_COUNTERS.setdefault(kind, default_registry().counter(
            "hvd_collective_calls_total", help="eager collective API calls",
            labels={"kind": kind}))
    counter.inc()


def _check_scales(values, prescale: float, postscale: float,
                  op: Optional[ReduceOp] = None) -> None:
    """Front-door validation so every backend rejects fractional scaling of
    integral tensors identically (the C++ core would otherwise truncate).
    AVERAGE is the same fractional 1/size postscale, so it is held to the
    same rule (the reference's torch path also errors: integer ``div_``)."""
    if prescale == 1.0 and postscale == 1.0 and op != ReduceOp.AVERAGE:
        return
    for v in values:
        dt = np.dtype(getattr(v, "dtype", None) or np.asarray(v).dtype)
        if op == ReduceOp.AVERAGE and np.issubdtype(dt, np.integer):
            raise ValueError(
                f"allreduce(op=Average) on an integral tensor ({dt}) would "
                "truncate; use op=Sum and divide, or cast to float first.")
        check_scale_dtype(dt, prescale)
        check_scale_dtype(dt, postscale)

_name_counter = [0]


def _auto_name(prefix: str, name: Optional[str]) -> str:
    if name is not None:
        return name
    _name_counter[0] += 1
    return f"{prefix}.noname.{_name_counter[0]}"


def _backend_for(process_set: ProcessSet) -> Backend:
    st = _require_init()
    return st.process_set_table.backend_for(process_set)


def _check_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    """Reference: ``handle_average_backwards_compatibility``
    (``horovod/common/util.py``)."""
    if average is not None:
        if op is not None:
            raise ValueError("Cannot specify both op and average.")
        return Average if average else Sum
    return Average if op is None else op


# -- allreduce --------------------------------------------------------------

def allreduce_async(value, average: Optional[bool] = None,
                    name: Optional[str] = None,
                    op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: ProcessSet = global_process_set) -> HvdHandle:
    op = _check_op(op, average)
    _check_scales([value], prescale_factor, postscale_factor, op)
    _count_call("allreduce")
    be = _backend_for(process_set)
    name = _auto_name("allreduce", name)
    pairs = _trace_enqueue("allreduce", name)
    with _spans.active_span(pairs[0][1]):
        h = be.allreduce_async(name, value, op, prescale_factor,
                               postscale_factor)
    return _trace_done(h, "allreduce", pairs)


def allreduce(value, average: Optional[bool] = None,
              name: Optional[str] = None, op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: ProcessSet = global_process_set):
    return allreduce_async(value, average, name, op, prescale_factor,
                           postscale_factor, process_set).wait()


def grouped_allreduce_async(values: Sequence, average: Optional[bool] = None,
                            name: Optional[str] = None,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: ProcessSet = global_process_set
                            ) -> HvdHandle:
    """Reference: ``grouped_allreduce_async_`` (``torch/mpi_ops.py:383``);
    grouping guarantees the tensors fuse into one collective
    (``GroupTable``, ``horovod/common/group_table.h:30-60``)."""
    op = _check_op(op, average)
    _check_scales(values, prescale_factor, postscale_factor, op)
    _count_call("grouped_allreduce")
    be = _backend_for(process_set)
    base = _auto_name("grouped_allreduce", name)
    names = [f"{base}.{i}" for i in range(len(values))]
    pairs = _trace_enqueue("grouped_allreduce", names)
    with _spans.active_span(pairs[0][1] if pairs else None):
        h = be.grouped_allreduce_async(names, list(values), op,
                                       prescale_factor, postscale_factor)
    return _trace_done(h, "grouped_allreduce", pairs)


def grouped_allreduce(values: Sequence, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: ProcessSet = global_process_set) -> List:
    return grouped_allreduce_async(values, average, name, op, prescale_factor,
                                   postscale_factor, process_set).wait()


# -- quantized allreduce ----------------------------------------------------
#
# Quantized payloads (per-block scales) are NOT sum-reducible on the
# wire, so the quantized path is allgather-of-codes + local dequantize
# and reduce (the 1-bit-SGD/EQuARX shape): each rank enqueues its int8
# values + fp32 scales (the C++ core fuses every leaf of a group into
# one negotiation cycle), moving ~4x fewer bytes than an fp32 ring
# allreduce would for the int8 codec.

def _wire_view(arr):
    """(wire array, restore fn): payload dtypes the core has no code for
    (fp8) travel as same-shape uint8 byte views."""
    a = np.asarray(arr)
    try:
        from horovod_tpu.core.core_backend import _np_dtype_code
        _np_dtype_code(a.dtype)
        return a, lambda g: g
    except Exception:
        if a.dtype.itemsize != 1:
            raise TypeError(
                f"cannot move {a.dtype} payload over the eager wire")
        return a.view(np.uint8), lambda g: g.view(a.dtype)


def quantized_grouped_allreduce_async(values: Sequence, quantizer,
                                      op: Optional[ReduceOp] = None,
                                      name: Optional[str] = None,
                                      process_set: ProcessSet =
                                      global_process_set) -> HvdHandle:
    """Allreduce a group of tensors with ``quantizer`` compressing the
    wire: quantize → fused allgather of (values, scales) → per-rank
    dequantize → local reduce. Only SUM and AVERAGE are defined for
    quantized payloads. Pre/wire bytes land on the compression metrics
    (``docs/OBSERVABILITY.md``)."""
    import threading

    from horovod_tpu.compression.metrics import record_compression
    from horovod_tpu.compression.quantizers import Quantized

    op = Average if op is None else op
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized allreduce supports Sum/Average, got {op}")
    _count_call("quantized_allreduce")
    base = _auto_name("quantized_allreduce", name)

    pre_bytes = 0
    wire_bytes = 0
    entries = []  # (value_handle, scale_handle, restore, spec, dtype)
    for i, value in enumerate(values):
        arr = jnp.asarray(value)
        q, spec = quantizer.quantize(arr)
        wire_vals, restore = _wire_view(q.values)
        pre_bytes += arr.size * arr.dtype.itemsize
        wire_bytes += q.wire_bytes
        # leading unit dim: allgather concatenates rank payloads on dim 0
        vh = allgather_async(wire_vals[None], name=f"{base}.{i}.values",
                             process_set=process_set)
        sh = allgather_async(np.asarray(q.scales)[None],
                             name=f"{base}.{i}.scales",
                             process_set=process_set)
        entries.append((vh, sh, restore, spec, arr.dtype))
    record_compression(quantizer.name, pre_bytes, wire_bytes)

    agg = HvdHandle()

    def waiter():
        try:
            outs = []
            for vh, sh, restore, spec, dtype in entries:
                gv = restore(np.asarray(vh.wait()))
                gs = np.asarray(sh.wait())
                parts = [quantizer.dequantize(
                    Quantized(jnp.asarray(gv[r]), jnp.asarray(gs[r])),
                    spec) for r in range(gv.shape[0])]
                out = parts[0]
                for p in parts[1:]:
                    out = out + p
                if op == ReduceOp.AVERAGE:
                    out = out / max(len(parts), 1)
                outs.append(out.astype(dtype))
            agg._set_result(outs)
        except BaseException as e:
            agg._set_error(e)

    threading.Thread(target=waiter, daemon=True).start()
    return agg


def quantized_grouped_allreduce(values: Sequence, quantizer,
                                op: Optional[ReduceOp] = None,
                                name: Optional[str] = None,
                                process_set: ProcessSet = global_process_set
                                ) -> List:
    return quantized_grouped_allreduce_async(
        values, quantizer, op, name, process_set).wait()


class _FirstOfHandle(HvdHandle):
    """Unwraps the single element of a grouped handle lazily at wait time
    (no extra waiter thread for the single-tensor convenience call)."""

    def __init__(self, inner: HvdHandle):
        super().__init__()
        self._inner = inner

    def poll(self) -> bool:
        return self._inner.poll()

    def wait(self, timeout: Optional[float] = None):
        if not self._event.is_set():
            try:
                self._set_result(self._inner.wait(timeout)[0])
            except TimeoutError:
                raise  # still in flight: stay retryable, don't finalize
            except BaseException as e:
                self._set_error(e)
        return super().wait(0)


def quantized_allreduce_async(value, quantizer,
                              op: Optional[ReduceOp] = None,
                              name: Optional[str] = None,
                              process_set: ProcessSet = global_process_set
                              ) -> HvdHandle:
    return _FirstOfHandle(quantized_grouped_allreduce_async(
        [value], quantizer, op, name, process_set))


def quantized_allreduce(value, quantizer, op: Optional[ReduceOp] = None,
                        name: Optional[str] = None,
                        process_set: ProcessSet = global_process_set):
    return quantized_allreduce_async(value, quantizer, op, name,
                                     process_set).wait()


# -- allgather --------------------------------------------------------------

def allgather_async(value, name: Optional[str] = None,
                    process_set: ProcessSet = global_process_set) -> HvdHandle:
    """Concat along dim 0 across ranks; ranks may differ in dim 0 (reference:
    ``EnqueueTensorAllgather`` ``operations.cc:1504-1556`` with per-rank
    first-dim sizes in the Response)."""
    _count_call("allgather")
    be = _backend_for(process_set)
    name = _auto_name("allgather", name)
    pairs = _trace_enqueue("allgather", name)
    with _spans.active_span(pairs[0][1]):
        h = be.allgather_async(name, value)
    return _trace_done(h, "allgather", pairs)


def allgather(value, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    return allgather_async(value, name, process_set).wait()


# -- broadcast --------------------------------------------------------------

def broadcast_async(value, root_rank: int, name: Optional[str] = None,
                    process_set: ProcessSet = global_process_set) -> HvdHandle:
    """``root_rank`` is the GLOBAL rank, also under process sets (reference:
    ``operations.cc:1560-1592`` converts global → set-relative internally)."""
    _count_call("broadcast")
    be = _backend_for(process_set)
    name = _auto_name("broadcast", name)
    pairs = _trace_enqueue("broadcast", name)
    with _spans.active_span(pairs[0][1]):
        h = be.broadcast_async(name, value, root_rank)
    return _trace_done(h, "broadcast", pairs)


def broadcast(value, root_rank: int, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    return broadcast_async(value, root_rank, name, process_set).wait()


# -- alltoall ---------------------------------------------------------------

def alltoall_async(value, splits: Optional[Sequence[int]] = None,
                   name: Optional[str] = None,
                   process_set: ProcessSet = global_process_set) -> HvdHandle:
    """Uneven alltoallv (reference: ``EnqueueTensorAlltoall``
    ``operations.cc:1630-1710``): ``splits[i]`` rows of dim 0 go to rank i;
    result is (received tensor, received splits)."""
    _count_call("alltoall")
    be = _backend_for(process_set)
    name = _auto_name("alltoall", name)
    pairs = _trace_enqueue("alltoall", name)
    with _spans.active_span(pairs[0][1]):
        h = be.alltoall_async(name, value, splits)
    return _trace_done(h, "alltoall", pairs)


def alltoall(value, splits: Optional[Sequence[int]] = None,
             name: Optional[str] = None,
             process_set: ProcessSet = global_process_set):
    return alltoall_async(value, splits, name, process_set).wait()


# -- reducescatter ----------------------------------------------------------

def reducescatter_async(value, op: Optional[ReduceOp] = None,
                        name: Optional[str] = None,
                        process_set: ProcessSet = global_process_set
                        ) -> HvdHandle:
    """Reduce-scatter over dim 0 (the reference added this in later versions;
    first-class here because ``reduce_scatter`` is the cheap half of a TPU
    ring allreduce and the core of ZeRO-style sharded optimizers)."""
    op = op if op is not None else Sum
    _count_call("reducescatter")
    be = _backend_for(process_set)
    name = _auto_name("reducescatter", name)
    pairs = _trace_enqueue("reducescatter", name)
    with _spans.active_span(pairs[0][1]):
        if be.size == 1:
            h = be.allreduce_async(name, value, op)
        else:
            h = be.reducescatter_async(name, value, op)
    return _trace_done(h, "reducescatter", pairs)


def reducescatter(value, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None,
                  process_set: ProcessSet = global_process_set):
    return reducescatter_async(value, op, name, process_set).wait()


# -- handles / control ------------------------------------------------------

def poll(handle: HvdHandle) -> bool:
    return handle.poll()


def synchronize(handle: HvdHandle):
    return handle.wait()


def join(device: int = -1) -> int:
    """Reference: ``hvd.join`` (``torch/mpi_ops.py:860-903``)."""
    st = _require_init()
    return st.backend.join(device)


def barrier(process_set: ProcessSet = global_process_set) -> None:
    _backend_for(process_set).barrier()
