"""Reduction-op enumeration (reference: ``horovod_reduce_op_average/sum/
adasum/min/max/product`` C API codes, ``horovod/common/operations.cc:1132-1160``
and the Python-side constants in each framework's ``mpi_ops.py``)."""

from __future__ import annotations

import enum


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Module-level aliases matching the reference's public names
# (``horovod.torch.mpi_ops.Average`` etc.).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
