"""Fused softmax cross-entropy as a Pallas TPU kernel.

The LM-training loss over a large vocabulary is memory-bound: XLA's
unfused path materializes [N, V] intermediates several times (shifted
logits, exp, normalizer broadcast). This kernel streams V-blocks through
VMEM keeping a flash-style running (max, sum) pair plus the label's
logit in scratch, so the forward reads the logits ONCE from HBM and
writes O(N) outputs (per-row loss + log-sum-exp residual).

  grid = (N/BLOCK_N, V/BLOCK_V)   — V-block innermost
  per row-block: for each v-block: online-softmax update; pick the
  label logit with an iota mask; at the last block emit
  loss = (m + log l) - z_label.

Differentiable via ``jax.custom_vjp``: the backward is the closed form
``dlogits = g · (softmax(logits) - onehot(labels))`` computed from the
saved log-sum-exp in one fused elementwise pass (no re-reduction) — the
dense [N, V] gradient write is unavoidable, everything else is O(N).

Same contract as :mod:`ops.pallas_attention` (reference analog: the
"write the hot op yourself" role of ``cuda_kernels.cu``): a pure-XLA
fallback runs on CPU or when shapes defeat the TPU tiling; a
non-multiple vocab is padded with ``NEG_INF`` columns inside the wrapper
(softmax ignores them), so the kernel still engages for real tokenizers'
vocab sizes (e.g. 30522, 32000).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

BLOCK_N = 128
BLOCK_V = 512


def _xent_kernel(labels_ref, logits_ref, loss_ref, lse_ref, m_ref, l_ref,
                 z_ref, *, block_v: int, n_v_blocks: int):
    """One (row-block, v-block) step; grid (nn, nv) with v innermost.

    All operands/scratch are kept >= 2-D ([bn, 1] trailing unit dims, the
    same Mosaic-friendly layout convention as ``_flash_kernel``)."""
    v_idx = pl.program_id(1)

    @pl.when(v_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        z_ref[:] = jnp.zeros_like(z_ref)

    s = logits_ref[...].astype(jnp.float32)            # [bn, bv]
    labels = labels_ref[...]                           # [bn, 1]
    off = v_idx * block_v
    cols = off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # the label's logit lives in exactly one v-block per row; an
    # out-of-range label never matches -> z stays 0 and loss = lse
    hit = cols == labels
    z_ref[:] = z_ref[...] + jnp.sum(jnp.where(hit, s, 0.0), axis=1,
                                    keepdims=True)

    m_prev = m_ref[...]                                # [bn, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    l_ref[:] = l_ref[...] * jnp.exp(m_prev - m_new) + \
        jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True)
    m_ref[:] = m_new

    @pl.when(v_idx == n_v_blocks - 1)
    def _emit():
        lse = m_ref[...] + jnp.log(l_ref[...])
        lse_ref[:] = lse
        loss_ref[:] = lse - z_ref[...]


def _xent_fwd_impl(logits, labels, block_n: int, block_v: int,
                   interpret: bool):
    n, v = logits.shape
    nn, nv = n // block_n, v // block_v
    loss, lse = pl.pallas_call(
        functools.partial(_xent_kernel, block_v=block_v, n_v_blocks=nv),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, block_v), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),   # m (running max)
            pltpu.VMEM((block_n, 1), jnp.float32),   # l (running sum)
            pltpu.VMEM((block_n, 1), jnp.float32),   # z (label logit)
        ],
        interpret=interpret,
    )(labels[:, None], logits)
    return loss[:, 0], lse[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_xent(logits, labels, block_n, block_v, interpret):
    loss, _ = _xent_fwd_impl(logits, labels, block_n, block_v, interpret)
    return loss


def _fused_xent_fwd(logits, labels, block_n, block_v, interpret):
    loss, lse = _xent_fwd_impl(logits, labels, block_n, block_v, interpret)
    return loss, (logits, labels, lse)


def _fused_xent_bwd(block_n, block_v, interpret, res, g):
    logits, labels, lse = res
    # one fused elementwise pass off the saved normalizer — XLA fuses
    # this into a single HBM sweep; the dense write is the gradient
    p = jnp.exp(logits.astype(jnp.float32) - lse[:, None])
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
              == labels[:, None])
    d = (p - onehot.astype(jnp.float32)) * g[:, None]
    return d.astype(logits.dtype), None


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


def _xla_xent(logits, labels):
    """Fallback with the SAME semantics as the kernel — deliberately NOT
    optax (which clips the gather index): an out-of-range label
    contributes no label logit, so loss = lse on BOTH paths and a CPU
    debug run reproduces the TPU loss bit-for-bit in that edge case."""
    lf = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(lf, axis=-1)
    v = lf.shape[-1]
    ok = (labels >= 0) & (labels < v)
    z = jnp.take_along_axis(
        lf, jnp.clip(labels, 0, v - 1)[..., None], axis=-1)[..., 0]
    return lse - jnp.where(ok, z, 0.0)


def fused_softmax_xent(logits: jax.Array, labels: jax.Array,
                       block_n: int = BLOCK_N, block_v: int = BLOCK_V,
                       interpret: bool = False) -> jax.Array:
    """Per-row ``-log softmax(logits)[label]`` with a single-pass fused
    TPU kernel; ``[..., V]`` logits and integer ``[...]`` labels of any
    leading shape. Vocab sizes that are not a ``block_v`` multiple are
    padded with ``NEG_INF`` columns (softmax-invisible) so the kernel
    still engages; rows that don't tile, or non-TPU backends without
    ``interpret=True``, fall back to the numerically identical XLA path.
    """
    v = logits.shape[-1]
    lead = logits.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    flat = logits.reshape(n, v)
    flat_labels = labels.reshape(n).astype(jnp.int32)

    on_tpu = jax.default_backend() == "tpu"
    if (not on_tpu and not interpret) or n % block_n != 0:
        return _xla_xent(flat, flat_labels).reshape(lead)

    pad = (-v) % block_v
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((n, pad), NEG_INF, flat.dtype)], axis=1)
    out = _fused_xent(flat, flat_labels, block_n, block_v, interpret)
    return out.reshape(lead)
