"""XLA collectives over a device mesh — the TPU data plane.

This replaces the reference's NCCL op implementations
(``horovod/common/ops/nccl_operations.cc:156-420``): instead of launching
``ncclAllReduce`` on a stream, collectives are expressed as
``jax.lax.psum``/``all_gather``/``all_to_all``/``ppermute`` inside
``shard_map`` over a named mesh and compiled by XLA onto ICI/DCN links.
Jitted callables are cached per (shape, dtype, mesh, axis, op) exactly the way
the reference caches NCCL communicators per (process set, device map, stream)
(``nccl_operations.cc:65-107``) — first call compiles, steady state replays.

Two API levels:

* **SPMD level** (use inside your own ``shard_map``/``jit``): ``preduce``,
  ``pallgather``, … — thin dispatchers over ``jax.lax`` collectives.
* **Array level** (eager-looking, used by tests and the single-controller
  backend): ``device_allreduce`` etc. take a global array whose leading dim
  indexes mesh-axis shards and run a cached jitted collective on it.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map

from horovod_tpu.ops.reduce_op import ReduceOp


# ---------------------------------------------------------------------------
# SPMD-level collectives (call inside shard_map / jit with named axes)
# ---------------------------------------------------------------------------

def preduce(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM
            ) -> jax.Array:
    """Cross-shard reduction along a named mesh axis.

    Dispatch mirrors the reference's reduce-op codes
    (``horovod_reduce_op_sum/average/...``, ``operations.cc:1132-1160``).
    """
    if op == ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op == ReduceOp.ADASUM:
        # Real VHDD-equivalent combine, not a plain sum (ADVICE r1): gather
        # all contributions and fold with the Adasum scaled-add tree.
        from horovod_tpu.ops.adasum import adasum_allreduce_along
        return adasum_allreduce_along(x, axis_name)
    if op == ReduceOp.AVERAGE:
        return lax.pmean(x, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    if op == ReduceOp.PRODUCT:
        # No hardware pprod; log-space would lose sign — use all_gather+prod.
        # Pin the accumulator dtype: jnp.prod would promote int32 -> int64.
        g = lax.all_gather(x, axis_name)
        return jnp.prod(g, axis=0, dtype=x.dtype)
    raise ValueError(f"Unsupported reduce op: {op}")


def pallgather(x: jax.Array, axis_name: str, axis: int = 0,
               tiled: bool = True) -> jax.Array:
    """All-gather along a named axis (reference allgather semantics: concat
    along dim 0, ``operations.cc:1504-1556``)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def preduce_scatter(x: jax.Array, axis_name: str, scatter_axis: int = 0
                    ) -> jax.Array:
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                            tiled=True)


def pbroadcast(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """Broadcast shard from ``root`` to all shards along ``axis_name``
    (reference: ``EnqueueTensorBroadcast``, ``operations.cc:1560-1626``)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    # psum promotes bool -> int; cast back to the input dtype
    return lax.psum(masked, axis_name).astype(x.dtype)


def palltoall(x: jax.Array, axis_name: str, split_axis: int = 0,
              concat_axis: int = 0) -> jax.Array:
    """Uniform all-to-all (reference: ``EnqueueTensorAlltoall``,
    ``operations.cc:1630-1710``; uneven splits live in
    :mod:`horovod_tpu.ops.alltoall`)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def preduce_quantized(x: jax.Array, axis_name: str, quantizer,
                      op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """Quantized allreduce along a named axis: ``reduce_scatter →
    quantize → all_gather → dequantize`` (EQuARX, arxiv 2506.17615).

    Quantizing only the GATHERED phase keeps the reduction itself exact:
    every shard's slice is summed in full precision by ``psum_scatter``,
    and only the already-reduced slices move quantized through the
    all-gather — so the end-to-end error is one quantization step, never
    a sum of per-rank quantization errors, while the gather (half the
    bytes of a ring allreduce) moves ~4x less with the int8 codec.

    Requires ``x.shape[0]`` divisible by the axis size (the scatter
    split); SUM and AVERAGE only.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized allreduce supports Sum/Average, got {op}")
    n = axis_size(axis_name)
    if x.ndim == 0:
        raise ValueError(
            "quantized allreduce needs at least a 1-D per-shard tensor "
            "(the scatter splits dim 0); use the exact path for scalars")
    if x.shape[0] % n != 0:
        raise ValueError(
            f"quantized allreduce needs dim 0 ({x.shape[0]}) divisible by "
            f"the axis size ({n}); pad the tensor or use the exact path")
    part = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVERAGE:
        part = part / n
    q, spec = quantizer.quantize(part)
    g_values = lax.all_gather(q.values, axis_name)  # [n, ...codes]
    g_scales = lax.all_gather(q.scales, axis_name)
    from horovod_tpu.compression.quantizers import Quantized
    parts = jax.vmap(
        lambda v, s: quantizer.dequantize(Quantized(v, s), spec)
    )(g_values, g_scales)
    return parts.reshape((n * part.shape[0],) + part.shape[1:]) \
        .astype(x.dtype)


def phier_allreduce(x: jax.Array, axis_name: str, topology,
                    op: ReduceOp = ReduceOp.SUM,
                    inter_codec=None,
                    small_floor: Optional[int] = None) -> jax.Array:
    """Topology-aware hierarchical allreduce along a named mesh axis:
    intra-host reduce_scatter → inter-host allreduce on the
    ``1/local_size``-sized shard → intra-host allgather.

    ``topology`` is a :class:`horovod_tpu.common.topology.MeshTopology`
    whose ``world`` must equal the axis size and whose hosts are
    contiguous along the axis (``detect_topology`` guarantees both).
    Only ``1/local_size`` of the payload crosses the slow inter-host
    fabric — the MLPerf TPU-pod decomposition (arxiv 1909.09756) and
    the reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` path.

    ``inter_codec`` (a :class:`~horovod_tpu.compression.quantizers.Quantizer`)
    quantizes ONLY the inter-host hop, EQuARX-style (arxiv 2506.17615):
    that hop becomes reduce_scatter (exact) → quantize → allgather →
    dequantize within each cross-host group, so the intra-host traffic
    stays full precision and the end-to-end error is one quantization
    step on the slow hop's bytes only.

    ``small_floor``: payloads under this many bytes skip the whole
    decomposition (and quantization) and take one flat ``psum`` — for
    latency-bound small tensors the two extra hops cost more than the
    bandwidth they save (the MLPerf paper's latency-optimized
    small-tensor path). Sizes are static under trace, so this is a
    compile-time branch.

    Sum/Average only. Numerics: every element is still a sum of the
    same ``n`` contributions, folded intra-host first — equal to flat
    ``psum`` up to fp reassociation (plus the documented codec bound on
    the inter-host hop when ``inter_codec`` is given).
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"hierarchical allreduce supports Sum/Average, got {op}")
    n = axis_size(axis_name)
    if topology.world != n:
        raise ValueError(
            f"topology {topology.num_hosts}x{topology.local_size} does "
            f"not cover axis {axis_name!r} of size {n}")
    nbytes = x.size * x.dtype.itemsize
    if not topology.is_hierarchical or \
            (small_floor and nbytes < small_floor):
        return preduce(x, axis_name, op)

    H, L = topology.num_hosts, topology.local_size
    intra = topology.intra_groups()
    inter = topology.inter_groups()
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    size = flat.size
    # one pad serves both scatters: intra splits by L, the (quantized)
    # inter hop splits the L-shard by H
    pad = (-size) % (L * H)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])

    # intra-host reduce_scatter: member l of host h holds shard l of the
    # host-local sum (group order == axis order, so shard l is slice l)
    part = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                            tiled=True, axis_index_groups=intra)

    if inter_codec is None:
        part = lax.psum(part, axis_name, axis_index_groups=inter)
        if op == ReduceOp.AVERAGE:
            part = part / n
    else:
        # EQuARX on the slow hop only: reduce_scatter across hosts is
        # exact; only the already-reduced 1/(L·H) slices travel
        # quantized through the cross-host allgather
        sub = lax.psum_scatter(part, axis_name, scatter_dimension=0,
                               tiled=True, axis_index_groups=inter)
        if op == ReduceOp.AVERAGE:
            sub = sub / n
        q, spec = inter_codec.quantize(sub)
        g_values = lax.all_gather(q.values, axis_name,
                                  axis_index_groups=inter)
        g_scales = lax.all_gather(q.scales, axis_name,
                                  axis_index_groups=inter)
        from horovod_tpu.compression.quantizers import Quantized
        parts = jax.vmap(
            lambda v, s: inter_codec.dequantize(Quantized(v, s), spec)
        )(g_values, g_scales)
        part = parts.reshape((H * sub.shape[0],) + sub.shape[1:]) \
            .astype(flat.dtype)

    # intra-host allgather reassembles the full vector on every device
    out = lax.all_gather(part, axis_name, axis=0, tiled=True,
                         axis_index_groups=intra)
    out = out.reshape(-1)
    if pad:
        out = out[:size]
    return out.reshape(orig_shape).astype(orig_dtype)


def pring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Ring permute — the building block for ring attention / ring allreduce
    overlap patterns (no reference analog; NCCL rings are internal to NCCL)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def pring_allreduce(x: jax.Array, axis_name: str,
                    op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """Chunked ring allreduce built from ``ppermute`` (2(n−1) steps of
    1/n-sized sends) instead of one monolithic ``psum``.

    This is the large-bucket path of the overlap engine
    (``train/overlap.py``): a single big ``psum`` is one indivisible
    collective on XLA's schedule, while the ring decomposes it into
    2(n−1) fine-grained permute steps the latency-hiding scheduler can
    interleave with the next microbatch's backward — the explicit-SPMD
    analog of NCCL's internal ring that the reference leans on
    (``docs/benchmarks.rst`` scaling story; MLPerf TPU-pod paper's
    latency-optimized decompositions, arxiv 1909.09756).

    SUM and AVERAGE only (the ring folds with ``+``). Works on any
    per-shard shape; internally flattens, pads to an ``n`` multiple and
    restores the shape. Numerics: each element is still a sum of the
    same ``n`` contributions, folded in ring order instead of psum's
    tree order — equal to ``psum`` up to fp reassociation. The ring
    moves and folds in the INPUT dtype (a bf16 bucket sends bf16 on
    every hop — same in-wire dtype a psum would use; cast to fp32
    first if you want fp32 accumulation).
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(f"ring allreduce supports Sum/Average, got {op}")
    n = axis_size(axis_name)
    if n == 1:
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    size = flat.size
    pad = (-size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    chunks = flat.reshape(n, -1)  # chunk c = slice c of the vector
    r = lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter: at step s every rank sends chunk (r−s) mod n to its
    # right neighbor, which folds it into the same chunk index — after
    # n−1 steps rank r owns the fully reduced chunk (r+1) mod n.
    for s in range(n - 1):
        send_idx = (r - s) % n
        recv_idx = (r - s - 1) % n
        moved = lax.ppermute(jnp.take(chunks, send_idx, axis=0),
                             axis_name, fwd)
        chunks = chunks.at[recv_idx].add(moved)

    # allgather: pass each completed chunk once around the ring.
    for s in range(n - 1):
        send_idx = (r + 1 - s) % n
        recv_idx = (r - s) % n
        moved = lax.ppermute(jnp.take(chunks, send_idx, axis=0),
                             axis_name, fwd)
        chunks = chunks.at[recv_idx].set(moved)

    out = chunks.reshape(-1)
    if pad:
        out = out[:size]
    if op == ReduceOp.AVERAGE:
        out = out / n
    return out.reshape(orig_shape).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Array-level collectives with jit caching
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1024)
def _cached_collective(kind: str, mesh: Mesh, axis_name: str,
                       op: ReduceOp, extra: Tuple) -> Callable:
    """Compile-once cache keyed like the reference's NCCL comm cache
    (``nccl_operations.h`` comm map keyed by process set + device map)."""
    if kind == "allreduce":
        def fn(x):
            # PRODUCT and ADASUM use all_gather whose replication across the
            # axis can't be statically inferred — disable the VMA check.
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(axis_name), out_specs=P(),
                               check_vma=(op not in (ReduceOp.PRODUCT,
                                                     ReduceOp.ADASUM)))
            def body(shard):
                return preduce(shard[0], axis_name, op)
            return body(x)
    elif kind == "allreduce_q":
        (quantizer,) = extra
        def fn(x):
            # quantize/dequantize shapes can't be VMA-inferred across the
            # gather — disable the check like the PRODUCT/ADASUM paths
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(axis_name), out_specs=P(),
                               check_vma=False)
            def body(shard):
                return preduce_quantized(shard[0], axis_name, quantizer, op)
            return body(x)
    elif kind == "allgather":
        def fn(x):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(axis_name), out_specs=P(),
                               check_vma=False)
            def body(shard):
                return pallgather(shard, axis_name, axis=0, tiled=True)
            return body(x)
    elif kind == "broadcast":
        (root,) = extra
        def fn(x):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(axis_name), out_specs=P())
            def body(shard):
                return pbroadcast(shard[0], axis_name, root)
            return body(x)
    elif kind == "alltoall":
        def fn(x):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(axis_name), out_specs=P(axis_name))
            def body(shard):
                return palltoall(shard, axis_name, 0, 0)
            return body(x)
    elif kind == "reducescatter":
        def fn(x):
            @functools.partial(shard_map, mesh=mesh,
                               in_specs=P(axis_name), out_specs=P(axis_name))
            def body(shard):
                # shard: [1, k, ...] — contribution of this shard; scatter
                # splits k across the axis.
                return preduce_scatter(shard[0], axis_name, 0)
            return body(x)
    else:
        raise ValueError(kind)
    return jax.jit(fn)


def _axis_n(mesh: Mesh, axis_name: str) -> int:
    return mesh.shape[axis_name]


def device_allreduce(x: jax.Array, mesh: Mesh, axis_name: str = "dp",
                     op: ReduceOp = ReduceOp.SUM,
                     compression=None) -> jax.Array:
    """Reduce over mesh-axis shards. ``x`` has leading dim == axis size; shard
    ``i`` is ``x[i]``; returns the reduction with that dim removed.

    ``compression`` (a :class:`horovod_tpu.compression.Quantizer`)
    selects the quantized path: reduce_scatter (exact) → quantize →
    all_gather → dequantize (:func:`preduce_quantized`), moving ~4x
    fewer gather bytes for the int8 codec. Requires the per-shard
    leading dim divisible by the axis size; Sum/Average only. Pre/wire
    byte accounting lands on the compression metrics from the static
    shapes here (host side — nothing recorded inside the jit)."""
    n = _axis_n(mesh, axis_name)
    assert x.shape[0] == n, (x.shape, n)
    if compression is None:
        return _cached_collective("allreduce", mesh, axis_name, op, ())(x)
    from horovod_tpu.compression.metrics import record_compression
    from horovod_tpu.compression.quantizers import Quantizer
    if not isinstance(compression, Quantizer):
        raise TypeError(
            "device_allreduce(compression=) takes a Quantizer (int8/fp8/"
            f"onebit); for dtype casts ({compression!r}) cast the input — "
            "the reduction runs natively in fp16/bf16")
    if x.ndim < 2:
        raise ValueError(
            "device_allreduce(compression=) needs at least 1-D shards "
            f"(got stacked shape {x.shape}: scalar per shard); the "
            "scatter phase splits the shard's dim 0 — use the exact path")
    out = _cached_collective("allreduce_q", mesh, axis_name, op,
                             (compression,))(x)
    # the gather phase moves the reduced tensor as n quantized slices
    # (each shard contributes its scatter slice); static-shape accounting
    slice_shape = (x.shape[1] // n,) + tuple(x.shape[2:])
    record_compression(compression.name,
                       int(x.size) // n * x.dtype.itemsize,
                       _quantized_wire_bytes(compression, slice_shape,
                                             jnp.dtype(x.dtype).name) * n)
    return out


@functools.lru_cache(maxsize=1024)
def _quantized_wire_bytes(quantizer, shape: Tuple, dtype: str) -> int:
    """Payload bytes ``quantizer`` puts on the wire for one ``shape``
    tensor — an abstract trace, cached on exactly the keys that determine
    it so the per-step hot path never re-traces the codec."""
    q_shape = jax.eval_shape(lambda s: quantizer.quantize(s)[0],
                             jax.ShapeDtypeStruct(shape, dtype))
    return (q_shape.values.size * q_shape.values.dtype.itemsize
            + q_shape.scales.size * q_shape.scales.dtype.itemsize)


def device_allgather(x: jax.Array, mesh: Mesh, axis_name: str = "dp"
                     ) -> jax.Array:
    """Identity-shaped allgather check: input leading dim sharded over axis;
    output is the full concatenation on every shard (returned once)."""
    return _cached_collective("allgather", mesh, axis_name, ReduceOp.SUM, ())(x)


def device_broadcast(x: jax.Array, mesh: Mesh, root: int = 0,
                     axis_name: str = "dp") -> jax.Array:
    n = _axis_n(mesh, axis_name)
    assert x.shape[0] == n
    return _cached_collective("broadcast", mesh, axis_name, ReduceOp.SUM,
                              (root,))(x)


def device_alltoall(x: jax.Array, mesh: Mesh, axis_name: str = "dp"
                    ) -> jax.Array:
    return _cached_collective("alltoall", mesh, axis_name, ReduceOp.SUM, ())(x)


def device_reduce_scatter(x: jax.Array, mesh: Mesh, axis_name: str = "dp"
                          ) -> jax.Array:
    return _cached_collective("reducescatter", mesh, axis_name,
                              ReduceOp.SUM, ())(x)
