"""Back-compat shim: the device-side profiling helpers moved to the
deep-profiling subsystem (:mod:`horovod_tpu.profiling` — ISSUE 9).

This module used to be a dead 50-line stub around
``jax.profiler.start_trace``; the real machinery now lives in
``horovod_tpu/profiling/``: :class:`ProfileManager` (bounded,
step-windowed captures driven on demand, from
``TelemetryCallback(profile_steps=...)``, or automatically by the
anomaly engine), compile observability, and HBM sampling.  Import from
``horovod_tpu.profiling`` in new code.
"""

from __future__ import annotations

from horovod_tpu.profiling import (ProfileManager, annotate,  # noqa: F401
                                   annotate_fn, default_manager,
                                   start_trace, stop_trace, trace)

__all__ = ["start_trace", "stop_trace", "trace", "annotate",
           "annotate_fn", "ProfileManager", "default_manager"]
