"""Device-side profiling annotations.

Reference: NVTX ranges wrapping each user-facing op for Nsight
(``horovod/common/nvtx_op_range.{h,cc}``, enqueue sites
``operations.cc:1455-1470``). TPU equivalent: ``jax.profiler`` traces +
named annotations that show up in XProf/TensorBoard, plus a context manager
pair mirroring ``hvd.start_timeline``/``stop_timeline`` for the device side.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


def start_trace(log_dir: str) -> None:
    """Begin a device trace viewable in TensorBoard/XProf (the device-side
    counterpart of ``hvd.start_timeline``)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named range on the device timeline (NVTX-range analog)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def annotate_fn(name: Optional[str] = None):
    """Decorator form: ``@annotate_fn("allreduce.grads")``."""
    def deco(fn):
        label = name or fn.__name__

        def wrapped(*args, **kwargs):
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)
        return wrapped
    return deco
