"""Ray cluster integration.

Reference: ``horovod/ray/runner.py`` — ``RayExecutor`` creates a placement
group of workers, a Coordinator collects each worker's host/rank info into
env vars, then all workers run the user fn (:41-360); elastic variant with
``RayHostDiscovery`` (``ray/elastic.py:38-149``).

Gated on ray availability (not bundled in this image).
"""

from __future__ import annotations

import socket
from typing import Any, Callable, Dict, List, Optional


def _require_ray():
    try:
        import ray
        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray requires the ray package, which is not "
            "installed in this environment. Install ray to use Ray-cluster "
            "launching; the rest of horovod_tpu works without it.") from e


class RayExecutor:
    """Reference: ``RayExecutor`` (``ray/runner.py:128-360``): start
    num_workers actors, coordinate env, run fns on all workers."""

    def __init__(self, num_workers: int = 1, cpus_per_worker: int = 1,
                 use_current_placement_group: bool = False,
                 env: Optional[Dict[str, str]] = None) -> None:
        self._ray = _require_ray()
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self._env = dict(env or {})
        self._workers: List[Any] = []
        self._has_executable = False

    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None) -> None:
        """Spin up the worker actors and wire the coordinator; with
        ``executable_cls``, also instantiate it on every worker for
        ``execute``/``execute_single`` (reference: ``RayExecutor.start``,
        ``ray/runner.py:250-280``)."""
        ray = self._ray
        self._has_executable = False  # a restart may drop the executable

        @ray.remote(num_cpus=self.cpus_per_worker)
        class _Worker:
            def __init__(self, rank: int, size: int,
                         base_env: Dict[str, str]) -> None:
                import os
                self.rank = rank
                os.environ.update(base_env)
                os.environ.update({
                    "HOROVOD_RANK": str(rank),
                    "HOROVOD_SIZE": str(size),
                })

            def hostname(self) -> str:
                return socket.gethostname()

            def pick_free_port(self) -> int:
                import socket as s
                sock = s.socket()
                sock.bind(("0.0.0.0", 0))
                port = sock.getsockname()[1]
                sock.close()
                return port

            def set_coordinator(self, addr: str, port: int) -> None:
                import os
                os.environ["HVD_TPU_COORD_ADDR"] = addr
                os.environ["HVD_TPU_COORD_PORT"] = str(port)

            def execute(self, fn_blob: bytes):
                import cloudpickle
                fn, args, kwargs = cloudpickle.loads(fn_blob)
                import horovod_tpu as hvd
                hvd.init()
                # return the VALUE (ray serializes it): run_remote futures
                # must resolve to results, reference-style
                return fn(*args, **kwargs)

            def make_executable(self, blob: bytes) -> None:
                # reference: start(executable_cls=...) instantiates the
                # user's class on every worker (ray/runner.py:250-280)
                import cloudpickle
                cls, a, k = cloudpickle.loads(blob)
                import horovod_tpu as hvd
                hvd.init()
                self.executable = cls(*a, **k)

            def execute_obj(self, fn_blob: bytes):
                import cloudpickle
                fn = cloudpickle.loads(fn_blob)
                return fn(self.executable)

            def shutdown(self) -> None:
                import horovod_tpu as hvd
                hvd.shutdown()

        self._workers = [
            _Worker.remote(r, self.num_workers, self._env)
            for r in range(self.num_workers)]
        # coordinator = rank 0's host (reference: Coordinator collecting
        # host info, ray/runner.py:41-128)
        ray = self._ray
        coord_host = ray.get(self._workers[0].hostname.remote())
        # the coordinator binds on rank 0's host, so pick the port THERE
        port = ray.get(self._workers[0].pick_free_port.remote())
        ray.get([w.set_coordinator.remote(coord_host, port)
                 for w in self._workers])
        if executable_cls is not None:
            import cloudpickle
            blob = cloudpickle.dumps((executable_cls,
                                      tuple(executable_args or ()),
                                      dict(executable_kwargs or {})))
            ray.get([w.make_executable.remote(blob)
                     for w in self._workers])
            self._has_executable = True

    def _require_started(self, need_executable: bool = False) -> None:
        if not self._workers:
            raise ValueError("RayExecutor: call start() first")
        if need_executable and not self._has_executable:
            raise ValueError(
                "RayExecutor: call start(executable_cls=...) first")

    def run_remote(self, fn: Callable, args: tuple = (),
                   kwargs: Optional[dict] = None) -> List[Any]:
        """Async variant (reference: ``run_remote``, ``ray/runner.py:312``):
        one future per worker; ``ray.get`` resolves them to the fns'
        return values."""
        import cloudpickle
        self._require_started()
        blob = cloudpickle.dumps((fn, args, kwargs or {}))
        return [w.execute.remote(blob) for w in self._workers]

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        return self._ray.get(self.run_remote(fn, args, kwargs))

    def execute(self, fn: Callable) -> List[Any]:
        """Apply ``fn(executable)`` on every worker (reference:
        ``RayExecutor.execute``, ``ray/runner.py:281``); requires
        ``start(executable_cls=...)``."""
        import cloudpickle
        self._require_started(need_executable=True)
        blob = cloudpickle.dumps(fn)
        return self._ray.get([w.execute_obj.remote(blob)
                              for w in self._workers])

    def execute_single(self, fn: Callable) -> Any:
        """Apply ``fn(executable)`` on the rank-0 worker only (reference:
        ``execute_single``, ``ray/runner.py:332``)."""
        import cloudpickle
        self._require_started(need_executable=True)
        blob = cloudpickle.dumps(fn)
        return self._ray.get(self._workers[0].execute_obj.remote(blob))

    def shutdown(self) -> None:
        ray = self._ray
        if self._workers:
            ray.get([w.shutdown.remote() for w in self._workers])
            for w in self._workers:
                ray.kill(w)
            self._workers = []


class RayHostDiscovery:
    """Host discovery over a live Ray cluster for the elastic driver
    (reference: ``RayHostDiscovery``, ``ray/elastic.py:38-88``): available
    hosts are Ray nodes with enough free CPUs for a worker slot."""

    def __init__(self, cpus_per_slot: int = 1) -> None:
        self._ray = _require_ray()
        self._cpus = cpus_per_slot

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        ray = self._ray
        hosts: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            cpus = int(node.get("Resources", {}).get("CPU", 0))
            slots = cpus // max(self._cpus, 1)
            if slots > 0:
                hosts[node["NodeManagerAddress"]] = slots
        return hosts


class ElasticRayExecutor:
    """Elastic executor over Ray (reference: ``ElasticRayExecutor``,
    ``ray/elastic.py:149+``). Two modes, matching the reference's two
    deployment styles:

    - ``run()`` with a ``command``: the generation-based elastic driver
      with Ray-node discovery; workers launch via ssh to Ray nodes.
    - ``run(fn)``: reference-style in-cluster execution — Ray actors host
      the shared agent transport (:mod:`horovod_tpu.runner.elastic.agent`)
      and the driver execs workers through them, no ssh; per-rank results
      of the completed generation are returned.

    Gated on ray availability."""

    def __init__(self, command=None, min_np: int = 1,
                 max_np: Optional[int] = None,
                 cpus_per_slot: int = 1, env: Optional[Dict[str, str]] = None,
                 reset_limit: Optional[int] = None) -> None:
        _require_ray()
        self._discovery = RayHostDiscovery(cpus_per_slot)
        self._command = command
        self._cpus = cpus_per_slot
        self._min_np = min_np
        self._max_np = max_np
        self._env = env
        self._reset_limit = reset_limit

    def start(self) -> None:
        """Reference API shape (``ElasticRayExecutor.start``): agents are
        created lazily by ``run(fn)``, so this only validates ray."""
        _require_ray()

    def run(self, fn: Optional[Callable] = None, args: tuple = (),
            kwargs: Optional[dict] = None):
        if fn is None:
            if self._command is None:
                raise ValueError("ElasticRayExecutor.run() needs either a "
                                 "constructor command or a fn argument")
            from horovod_tpu.runner.elastic.driver import ElasticDriver
            driver = ElasticDriver(self._discovery, self._command,
                                   min_np=self._min_np,
                                   max_np=self._max_np,
                                   env=self._env,
                                   reset_limit=self._reset_limit)
            return driver.run()
        return self._run_fn(fn, args, kwargs)

    def _run_fn(self, fn: Callable, args: tuple, kwargs: Optional[dict]):
        import time as _time
        ray = _require_ray()
        from horovod_tpu.runner.elastic.agent import run_agent_elastic

        @ray.remote(num_cpus=self._cpus)
        class _AgentActor:
            def run_agent(self, ordinal, kv_addr, kv_port, secret_hex,
                          world_secret_hex):
                from horovod_tpu.runner.elastic.agent import (
                    agent_loop, resolve_kv_addr)
                agent_loop(int(ordinal), resolve_kv_addr(kv_addr),
                           kv_port, secret_hex, world_secret_hex)
                return True

        def start_agents(ctx):
            import json as _json
            import threading
            from horovod_tpu.runner.elastic.agent import STALE_S

            kv = ctx["kv"]  # in-process server handle (driver side)
            port = ctx["kv_port"]
            actors = []
            stop = threading.Event()
            next_ordinal = [0]

            def spawn():
                a = _AgentActor.remote()
                a.run_agent.remote(next_ordinal[0], ctx["kv_addr"], port,
                                   ctx["secret_hex"],
                                   ctx["world_secret_hex"])
                next_ordinal[0] += 1
                actors.append(a)

            for _ in range(ctx["max_np"]):
                spawn()

            def fresh_agent_count():
                n = 0
                for blob in kv.scope("agents").values():
                    if _time.time() - _json.loads(blob)["ts"] < STALE_S:
                        n += 1
                return n

            def respawner():
                # Ray actors are not auto-restarted (unlike Spark task
                # retry): top the registry back up to max_np when actor
                # loss shrinks it, so the driver can grow back. Bounded:
                # a replacement that never registers (no capacity, node
                # permanently gone) must not turn into an unbounded
                # stream of pending actors
                budget = 4 * ctx["max_np"]
                misses = 0
                while not stop.wait(5.0):
                    misses = misses + 1 \
                        if fresh_agent_count() < ctx["max_np"] else 0
                    if misses >= 2 and budget > 0:
                        spawn()
                        budget -= 1
                        misses = -4  # cooldown: let the replacement land

            mon = threading.Thread(target=respawner, daemon=True)
            mon.start()

            def cleanup():
                stop.set()
                mon.join(timeout=10)
                # shutdown is already posted; give loops one poll cycle to
                # exit cleanly, then reclaim the actors
                _time.sleep(1.0)
                for a in actors:
                    ray.kill(a)
            return cleanup

        return run_agent_elastic(
            start_agents, fn, args, kwargs,
            num_proc=self._max_np or self._min_np, min_np=self._min_np,
            max_np=self._max_np, env=self._env,
            reset_limit=self._reset_limit)
