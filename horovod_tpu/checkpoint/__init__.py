"""TPU-native durable sharded checkpointing.

The elastic design's missing leg (ROADMAP robustness): the reference's
``State.commit()`` snapshots to host memory and our port's per-host
pickle dies with the host that wrote it.  This subsystem gives
``commit()`` a durable, dependency-free backend:

* :class:`ShardedCheckpointer` — async two-phase-commit store: each
  rank writes only its shards (npz + sha256 marker), rank 0 writes the
  manifest and atomically renames ``step_N.tmp`` → ``step_N``; restore
  reassembles global arrays and re-slices them onto the *current*
  mesh/world size (elastic resharding).
* :mod:`~horovod_tpu.checkpoint.format` — the on-disk contract (spec
  version, manifests, shard markers, GC helpers).
* :mod:`~horovod_tpu.checkpoint.metrics` — save/restore bytes and
  duration histograms + inflight gauge on the process-wide ``/metrics``
  registry.

Integration points: ``elastic.ObjectState`` commits through this store
when ``HVD_TPU_ELASTIC_DURABLE`` is on (docs/ELASTIC.md),
``train.callbacks.CheckpointCallback`` wires it into training loops,
and ``train.checkpoint`` is a back-compat shim whose orbax path is now
optional.
"""

from horovod_tpu.checkpoint.format import CheckpointError  # noqa: F401
from horovod_tpu.checkpoint.store import ShardedCheckpointer  # noqa: F401

__all__ = ["CheckpointError", "ShardedCheckpointer"]
