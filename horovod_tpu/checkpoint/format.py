"""On-disk format of the native sharded checkpoint store.

No reference analog: the reference's only durable artifacts are the
framework files its Spark Store writes; core elastic state lives in host
memory (SURVEY.md §5).  Here the layout is a two-phase commit any POSIX
(or NFS-consistent) filesystem can honor:

::

    <base>/
      step_12/                      # committed checkpoint (atomic rename)
        manifest.json               # rank 0, written LAST inside the tmp dir
        shard_0.npz  shard_0.json   # per-rank payload + {sha256, entries}
        shard_1.npz  shard_1.json
      step_13.tmp/                  # in-flight or abandoned (crash) — never
                                    # read by restore, reclaimed by GC

Phase 1: every rank serializes its shard to ``shard_<r>.npz`` (write →
fsync → rename from ``*.part``) and then publishes ``shard_<r>.json``
(the completion marker, carrying the payload's sha256 and the index
ranges of every entry).  Phase 2: rank 0 waits for all W markers, writes
``manifest.json`` (global shapes/dtypes, shard→rank map, world size,
spec version, per-file sha256), fsyncs, and atomically renames
``step_N.tmp`` → ``step_N``.  A crash at ANY point — including kill -9
of a writer — leaves either a complete committed checkpoint or a tmp
dir that readers ignore and GC reclaims.

Everything here is stdlib + numpy; arrays with dtypes the ``.npy``
format cannot carry natively (bfloat16, float8_*) are stored as
same-width uint views with the logical dtype recorded in the manifest.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

SPEC_VERSION = 1
MANIFEST = "manifest.json"
ATTEMPT = "attempt.json"
TMP_SUFFIX = ".tmp"
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"^step_(\d+)\.tmp$")

# .npy serializes these directly; anything else rides a uint view of the
# same itemsize and is re-viewed on load (bf16 would otherwise come back
# as an opaque void dtype).
_NATIVE_KINDS = frozenset("biufc")
_NATIVE_DTYPES = frozenset(
    np.dtype(t).name for t in (
        np.bool_, np.int8, np.int16, np.int32, np.int64,
        np.uint8, np.uint16, np.uint32, np.uint64,
        np.float16, np.float32, np.float64,
        np.complex64, np.complex128))


class CheckpointError(RuntimeError):
    """A save could not commit or a restore found a broken checkpoint."""


def step_dir(base: str, step: int) -> str:
    return os.path.join(base, f"step_{int(step)}")


def tmp_dir(base: str, step: int) -> str:
    return step_dir(base, step) + TMP_SUFFIX


def shard_npz(rank: int) -> str:
    return f"shard_{int(rank)}.npz"


def shard_meta(rank: int) -> str:
    return f"shard_{int(rank)}.json"


def list_steps(base: str) -> List[int]:
    """Committed steps (dirs named ``step_N`` that contain a manifest),
    ascending.  Tmp dirs and manifest-less dirs are invisible here by
    construction — they are either in-flight or wreckage."""
    steps = []
    try:
        names = os.listdir(base)
    except OSError:
        return []
    for name in names:
        m = _STEP_RE.match(name)
        if m and os.path.isfile(os.path.join(base, name, MANIFEST)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def list_tmp_steps(base: str) -> List[Tuple[int, str]]:
    """``(step, path)`` of every in-flight/abandoned tmp dir."""
    out = []
    try:
        names = os.listdir(base)
    except OSError:
        return []
    for name in names:
        m = _TMP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(base, name)))
    return sorted(out)


def list_broken_steps(base: str) -> List[Tuple[int, str]]:
    """``(step, path)`` of ``step_N`` dirs WITHOUT a manifest — can't
    arise from this writer (the rename happens after the manifest) but
    tampering/partial copies produce them; readers ignore them and GC
    reclaims them."""
    out = []
    try:
        names = os.listdir(base)
    except OSError:
        return []
    for name in names:
        m = _STEP_RE.match(name)
        if m and not os.path.isfile(os.path.join(base, name, MANIFEST)):
            out.append((int(m.group(1)), os.path.join(base, name)))
    return sorted(out)


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def fsync_dir(path: str) -> None:
    """Durability of the rename itself (best-effort: not every
    filesystem supports directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_atomic(path: str, data: bytes) -> None:
    """write → fsync → rename, so ``path`` never holds a torn file."""
    part = path + ".part"
    with open(part, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(part, path)


def shard_bounds(dim: int, world: int) -> List[Tuple[int, int]]:
    """Even contiguous split of axis length ``dim`` over ``world`` ranks
    (some ranks may get an empty range).  Deterministic — both the save
    planner and any reader can recompute it from the manifest's world
    size."""
    w = max(1, int(world))
    return [(r * dim // w, (r + 1) * dim // w) for r in range(w)]


def storage_view(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """``(storable, logical_dtype_name)`` — exotic dtypes become uint
    views of the same width."""
    dt = arr.dtype
    if dt.kind in _NATIVE_KINDS and dt.name in _NATIVE_DTYPES:
        return arr, dt.name
    store = np.ascontiguousarray(arr).view(
        np.dtype(f"uint{dt.itemsize * 8}"))
    return store, dt.name


def np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8_* with numpy

        return np.dtype(getattr(ml_dtypes, name))


def logical_view(arr: np.ndarray, dtype_name: str) -> np.ndarray:
    if arr.dtype.name == dtype_name:
        return arr
    return arr.view(np_dtype(dtype_name))


def normalize_index(index: Sequence, shape: Sequence[int]) -> List[List[int]]:
    """A shard's position as ``[[start, stop], ...]`` per dim (JSON-safe;
    accepts the slice tuples of ``jax.Array.addressable_shards``)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def index_slices(index: Sequence[Sequence[int]]) -> Tuple[slice, ...]:
    return tuple(slice(int(s), int(e)) for s, e in index)


def open_attempt(dirpath: str, nonce: str) -> None:
    """Rank 0 claims the tmp dir for ONE save attempt.  Peers write
    their shard markers only after seeing the token and embed its nonce
    — so a marker left by a crashed earlier attempt (different/absent
    nonce) can never satisfy this attempt's commit barrier."""
    os.makedirs(dirpath, exist_ok=True)
    write_atomic(os.path.join(dirpath, ATTEMPT),
                 json.dumps({"nonce": nonce}).encode())
    fsync_dir(dirpath)


def read_attempt(dirpath: str) -> Optional[str]:
    try:
        with open(os.path.join(dirpath, ATTEMPT), "rb") as f:
            doc = json.loads(f.read())
        return doc.get("nonce") or None
    except (OSError, ValueError, AttributeError):
        return None


def write_shard(dirpath: str, rank: int,
                arrays: Dict[str, np.ndarray],
                entries: List[dict],
                attempt: Optional[str] = None) -> str:
    """Phase 1 for one rank: payload npz (atomic), then the completion
    marker ``shard_<rank>.json`` with the payload sha256 + entry index
    map + the attempt nonce.  The marker's existence tells rank 0 this
    rank is done.  Returns the payload's sha256."""
    os.makedirs(dirpath, exist_ok=True)
    npz_path = os.path.join(dirpath, shard_npz(rank))
    part = npz_path + ".part"
    with open(part, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(part, npz_path)
    sha = file_sha256(npz_path)
    meta = {"version": SPEC_VERSION, "rank": int(rank), "sha256": sha,
            "attempt": attempt, "entries": entries}
    write_atomic(os.path.join(dirpath, shard_meta(rank)),
                 json.dumps(meta, sort_keys=True).encode())
    fsync_dir(dirpath)
    return sha


def read_shard_meta(dirpath: str, rank: int) -> Optional[dict]:
    path = os.path.join(dirpath, shard_meta(rank))
    try:
        with open(path, "rb") as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


def commit(base: str, step: int, manifest: dict) -> None:
    """Phase 2: manifest into the tmp dir, then the atomic rename that
    makes the checkpoint exist."""
    tmp = tmp_dir(base, step)
    final = step_dir(base, step)
    write_atomic(os.path.join(tmp, MANIFEST),
                 json.dumps(manifest, sort_keys=True).encode())
    fsync_dir(tmp)
    if os.path.exists(final):
        raise CheckpointError(f"checkpoint step {step} already exists "
                              f"at {final}")
    os.rename(tmp, final)
    fsync_dir(base)


def read_manifest(base: str, step: int) -> dict:
    path = os.path.join(step_dir(base, step), MANIFEST)
    try:
        with open(path, "rb") as f:
            manifest = json.loads(f.read())
    except OSError as e:
        raise CheckpointError(
            f"no committed checkpoint for step {step} under {base}") from e
    except ValueError as e:
        raise CheckpointError(f"corrupt manifest at {path}") from e
    version = manifest.get("version")
    if version != SPEC_VERSION:
        raise CheckpointError(
            f"checkpoint spec version {version!r} at {path} is not "
            f"readable by this build (expects {SPEC_VERSION})")
    return manifest


def remove_tree(path: str) -> None:
    shutil.rmtree(path, ignore_errors=True)


def newest_mtime(path: str) -> float:
    """The most recent mtime inside a dir (the dir itself included) —
    GC's liveness signal for tmp dirs another process may still be
    filling."""
    try:
        newest = os.path.getmtime(path)
    except OSError:
        return 0.0
    try:
        names = os.listdir(path)
    except OSError:
        return newest
    for name in names:
        try:
            newest = max(newest, os.path.getmtime(os.path.join(path, name)))
        except OSError:
            continue
    return newest
