"""The native sharded checkpoint store.

``ShardedCheckpointer`` is the durable backend the elastic design needs
(reference: ``State.commit()`` semantics in ``common/elastic.py``; here
commits survive host loss, which the reference's host-memory snapshots
and our per-host pickle cannot).  Design (docs/ELASTIC.md "Durable
commits"):

* **Sharded** — each rank writes only its shards: slices of globally
  replicated arrays are partitioned by rank along axis 0 (so W ranks
  each write ~1/W of the bytes), and multi-controller ``jax.Array``\\ s
  that are NOT fully addressable contribute exactly the shards this
  process owns (``addressable_shards`` with ``replica_id == 0``).
* **Two-phase commit** — shards + per-file sha256 markers first, then a
  rank-0 manifest and an atomic ``step_N.tmp`` → ``step_N`` rename
  (:mod:`horovod_tpu.checkpoint.format`).  The commit barrier is the
  filesystem itself (rank 0 waits for all W markers), so no collective
  is needed and a kill -9 anywhere leaves the previous checkpoint
  intact.
* **Async** — the device→host snapshot (the consistent cut) is inline;
  serialization/fsync/commit run on a background writer with an
  inflight cap (:mod:`horovod_tpu.checkpoint.writer`).
* **Elastic resharding restore** — restore reassembles global arrays
  from the manifest's shard map and re-slices them onto the CURRENT
  mesh via ``like`` shardings; the manifest's world size need not match
  the current one, which is exactly what ``hvd.elastic`` re-meshing
  needs.

Replication contract: leaves that are not multi-controller
``jax.Array``\\ s must hold the same value on every rank when ``save``
is called (true for anything that went through ``State.sync()`` /
allreduce-averaged training state) — rank r's axis-0 slice stands in
for everyone's.
"""

from __future__ import annotations

import base64
import hashlib
import io
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.checkpoint import format as fmt
from horovod_tpu.checkpoint import metrics as ckpt_metrics
from horovod_tpu.checkpoint.format import CheckpointError
from horovod_tpu.checkpoint.writer import AsyncWriter
from horovod_tpu.common.config import env_float, env_int
from horovod_tpu.common.logging import get_logger

_INLINE_KINDS = ("bool", "int", "float", "str")


def _default_rank() -> int:
    try:
        from horovod_tpu.common.basics import is_initialized, rank
        if is_initialized():
            return rank()
    except Exception:
        pass
    return int(os.environ.get("HOROVOD_RANK",
                              os.environ.get("HVD_TPU_RANK", "0")))


def _default_world() -> int:
    try:
        from horovod_tpu.common.basics import is_initialized, size
        if is_initialized():
            return size()
    except Exception:
        pass
    return int(os.environ.get("HOROVOD_SIZE",
                              os.environ.get("HVD_TPU_SIZE", "1")))


def _path_parts(path) -> List[dict]:
    """JSON-safe serialization of a key path (used by the ``like``-less
    restore fallback to rebuild nesting)."""
    import jax.tree_util as jtu
    out: List[dict] = []
    for e in path:
        if isinstance(e, jtu.DictKey):
            key = e.key
            if not isinstance(key, (str, int, float, bool)):
                key = repr(key)
            out.append({"k": key})
        elif isinstance(e, jtu.SequenceKey):
            out.append({"i": int(e.idx)})
        elif isinstance(e, jtu.GetAttrKey):
            out.append({"a": e.name})
        else:  # FlattenedIndexKey and friends
            out.append({"i": int(getattr(e, "key", 0))})
    return out


def _full_index(shape: Tuple[int, ...]) -> List[List[int]]:
    return [[0, int(d)] for d in shape]


def _is_multicontroller(value: Any) -> bool:
    import jax
    return isinstance(value, jax.Array) and \
        not getattr(value, "is_fully_addressable", True)


class _Plan:
    """One rank's share of one save: manifest leaf records (rank 0 uses
    them), the npz payload, and the per-entry index map for the shard
    marker."""

    def __init__(self) -> None:
        self.leaves: List[dict] = []
        self.arrays: Dict[str, np.ndarray] = {}
        self.entries: List[dict] = []
        self.nbytes = 0
        self.treedef: Optional[str] = None

    def add_entry(self, leaf_idx: int, index: List[List[int]],
                  data: np.ndarray) -> None:
        key = f"L{leaf_idx}S{len(self.entries)}"
        self.arrays[key] = data
        self.entries.append({"key": key, "leaf": leaf_idx, "index": index})
        self.nbytes += int(data.nbytes)


class ShardedCheckpointer:
    """Durable (step → pytree) checkpoint store; drop-in for the old
    orbax wrapper's surface (``save``/``restore``/``restore_latest``/
    ``latest_step``/``close``) with async saves by default.

    Usage::

        ckpt = ShardedCheckpointer("/ckpt/run1")
        ckpt.save(step, {"params": params, "opt_state": opt_state})
        ...
        state = ckpt.restore_latest(like=state)   # onto the CURRENT mesh
    """

    def __init__(self, directory: str,
                 max_to_keep: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 commit_timeout_s: Optional[float] = None,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 verify: bool = True) -> None:
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._max_to_keep = env_int("CHECKPOINT_MAX_TO_KEEP", 3) \
            if max_to_keep is None else int(max_to_keep)
        self._commit_timeout = env_float("CHECKPOINT_COMMIT_TIMEOUT_S", 120.0) \
            if commit_timeout_s is None else float(commit_timeout_s)
        self._rank = _default_rank() if rank is None else int(rank)
        self._world = _default_world() if world_size is None else \
            max(1, int(world_size))
        self._verify = verify
        self._lock = threading.Lock()
        self._inflight_steps: set = set()
        inflight = env_int("CHECKPOINT_INFLIGHT", 2) \
            if max_inflight is None else int(max_inflight)
        self._writer = AsyncWriter(max_inflight=inflight,
                                   on_inflight=ckpt_metrics.set_inflight)

    @property
    def directory(self) -> str:
        return self._dir

    # ------------------------------------------------------------- save

    def save(self, step: int, state: Any, wait: bool = False) -> None:
        """Snapshot ``state`` device→host NOW (the consistent cut) and
        write it in the background; ``wait=True`` blocks until this
        rank's shard is durable — and, on rank 0, until the checkpoint
        is committed."""
        step = int(step)
        with self._lock:
            if step in self._inflight_steps:
                raise CheckpointError(f"step {step} is already being saved")
            if os.path.isdir(fmt.step_dir(self._dir, step)):
                raise CheckpointError(
                    f"step {step} already committed under {self._dir}")
            self._inflight_steps.add(step)
        tmp = fmt.tmp_dir(self._dir, step)
        if self._rank == 0 and os.path.isdir(tmp):
            # a tmp dir for this step means a crashed earlier attempt:
            # its shard markers must NOT satisfy the commit barrier (they
            # describe another generation's state).  Clearing the slate
            # here, before phase 1 starts, means the worst race — a fast
            # fresh peer already wrote here — costs a LOUD commit
            # timeout this round, never a silently mixed checkpoint.
            fmt.remove_tree(tmp)
        t_inline = time.monotonic()
        try:
            plan = self._snapshot(state)
        except BaseException:
            with self._lock:
                self._inflight_steps.discard(step)
            raise
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event("ckpt_save", step=step, rank=self._rank)
        import uuid
        job = self._make_job(step, plan, uuid.uuid4().hex)
        try:
            self._writer.submit(job)
        except BaseException:
            with self._lock:
                self._inflight_steps.discard(step)
            raise
        if wait:
            self.wait()
        # goodput ledger: only the slice that BLOCKED the caller counts
        # as checkpoint_stall — the inline device→host cut plus a
        # waited-for commit; the background shard write is free wall
        # time (it overlaps training) and stays out of the books
        try:
            from horovod_tpu.metrics import goodput
            goodput.note_checkpoint_stall(time.monotonic() - t_inline)
        except Exception:
            pass

    def wait(self) -> None:
        """Drain queued saves; re-raises the first background failure."""
        self._writer.wait()

    def check_error(self) -> None:
        """Re-raise (and clear) a pending background-save failure
        without waiting for in-flight saves."""
        self._writer.check()

    # orbax-API parity for callers of the old wrapper
    wait_until_finished = wait

    def close(self, wait: bool = True) -> None:
        """``wait=False`` abandons queued saves (in-flight commits are
        nonce-protected; their tmp dirs fall to GC) — for callers that
        must not stall behind a commit waiting on a dead peer."""
        self._writer.close(wait=wait)

    def _snapshot(self, state: Any) -> _Plan:
        import jax
        import jax.tree_util as jtu
        flat, treedef = jtu.tree_flatten_with_path(state)
        plan = _Plan()
        for li, (path, value) in enumerate(flat):
            rec = {"path": jtu.keystr(path), "parts": _path_parts(path)}
            # arrays/np scalars FIRST: np.float64 subclasses python
            # float, and the inline branch would strip its dtype
            if _is_multicontroller(value):
                self._plan_global_array(plan, li, rec, value)
            elif isinstance(value, (jax.Array, np.ndarray, np.generic)):
                self._plan_replicated_array(plan, li, rec, value)
            elif isinstance(value, bool):
                rec.update(kind="bool", value=value)
            elif isinstance(value, int):
                rec.update(kind="int", value=value)
            elif isinstance(value, float):
                rec.update(kind="float", value=value)
            elif isinstance(value, str):
                rec.update(kind="str", value=value)
            else:
                self._plan_pickle(plan, li, rec, value)
            plan.leaves.append(rec)
        try:
            rec_td = base64.b64encode(pickle.dumps(treedef)).decode("ascii")
        except Exception:
            rec_td = None  # like=/parts fallback still restores
        plan.treedef = rec_td
        return plan

    def _plan_global_array(self, plan: _Plan, li: int, rec: dict,
                           value: Any) -> None:
        """Multi-controller ``jax.Array``: this process contributes
        exactly the shards it owns."""
        shape = tuple(int(d) for d in value.shape)
        dtype_name = None
        for shard in value.addressable_shards:
            if shard.replica_id != 0:
                continue  # one owner per shard across the replica group
            # copy=True: the cut must own its bytes — a zero-copy view
            # of a device buffer is unsafe once the caller donates it
            host = np.array(shard.data, copy=True)
            store_arr, dtype_name = fmt.storage_view(host)
            plan.add_entry(li, fmt.normalize_index(shard.index, shape),
                           store_arr)
        if dtype_name is None:  # no owned shards; still record the leaf
            _, dtype_name = fmt.storage_view(
                np.empty((), fmt.np_dtype(str(value.dtype))))
        rec.update(kind="array", shape=list(shape), dtype=dtype_name,
                   scalar=False)

    def _plan_replicated_array(self, plan: _Plan, li: int, rec: dict,
                               value: Any) -> None:
        """Replicated array: rank r owns the r-th contiguous axis-0
        slice (rank 0 owns small/0-d arrays whole).  The slice is taken
        BEFORE the host copy, so each rank moves ~1/W of the bytes
        device→host and the writer queue pins only the slice — and
        copy=True throughout: np.ndarray leaves may be mutated by the
        caller before the background write lands, and a zero-copy view
        of a jax CPU buffer is unsafe once the caller donates it."""
        scalar = isinstance(value, np.generic)
        shape = tuple(int(d) for d in np.shape(value))
        dt = value.dtype if hasattr(value, "dtype") else \
            np.asarray(value).dtype
        _, dtype_name = fmt.storage_view(np.empty((), dt))
        rec.update(kind="array", shape=list(shape), dtype=dtype_name,
                   scalar=scalar)
        if self._world == 1 or len(shape) == 0 or shape[0] == 0:
            if self._rank == 0:
                store_arr, _ = fmt.storage_view(np.array(value, copy=True))
                plan.add_entry(li, _full_index(shape), store_arr)
            return
        start, stop = fmt.shard_bounds(shape[0], self._world)[self._rank]
        if stop > start:
            store_arr, _ = fmt.storage_view(
                np.array(value[start:stop], copy=True))
            index = [[start, stop]] + _full_index(shape[1:])
            plan.add_entry(li, index, store_arr)

    def _plan_pickle(self, plan: _Plan, li: int, rec: dict,
                     value: Any) -> None:
        payload = pickle.dumps(value)
        rec.update(kind="pickle", shape=[len(payload)], dtype="uint8",
                   scalar=False)
        if self._rank == 0:
            plan.add_entry(li, [[0, len(payload)]],
                           np.frombuffer(payload, np.uint8))

    def _make_job(self, step: int, plan: _Plan, nonce: str):
        def job() -> None:
            t0 = time.monotonic()
            tmp = fmt.tmp_dir(self._dir, step)
            try:
                if self._rank == 0:
                    fmt.open_attempt(tmp, nonce)
                else:
                    nonce_seen = self._await_attempt(step, tmp)
                fmt.write_shard(tmp, self._rank, plan.arrays, plan.entries,
                                attempt=nonce if self._rank == 0
                                else nonce_seen)
                if self._rank == 0:
                    self._commit(step, plan, tmp, nonce)
            except BaseException:
                ckpt_metrics.record_failure()
                raise
            finally:
                with self._lock:
                    self._inflight_steps.discard(step)
            ckpt_metrics.record_save(plan.nbytes, time.monotonic() - t0,
                                     step)
            if self._rank == 0:
                try:
                    self.gc()
                except Exception:
                    pass  # GC is advisory; never fail a commit over it

        return job

    def _await_attempt(self, step: int, tmp: str) -> str:
        """Non-zero ranks write only into an attempt rank 0 has opened —
        the nonce handshake is what makes a crashed generation's
        leftovers inert."""
        deadline = time.monotonic() + self._commit_timeout
        while True:
            nonce = fmt.read_attempt(tmp)
            if nonce is not None:
                return nonce
            if time.monotonic() >= deadline:
                raise CheckpointError(
                    f"rank {self._rank}: no attempt token from rank 0 "
                    f"for step {step} after {self._commit_timeout:.0f}s")
            time.sleep(0.05)

    def _commit(self, step: int, plan: _Plan, tmp: str,
                nonce: str) -> None:
        """Rank 0's phase 2: wait for every rank's shard marker FROM
        THIS ATTEMPT, then manifest + atomic rename.  On timeout the
        tmp dir is LEFT IN PLACE — a peer may still be writing; GC
        reclaims it once idle."""
        deadline = time.monotonic() + self._commit_timeout
        metas: Dict[int, dict] = {}
        while True:
            for r in range(self._world):
                if r not in metas:
                    meta = fmt.read_shard_meta(tmp, r)
                    if meta is not None and meta.get("attempt") == nonce:
                        metas[r] = meta
            if len(metas) == self._world:
                break
            if time.monotonic() >= deadline:
                missing = sorted(set(range(self._world)) - set(metas))
                raise CheckpointError(
                    f"commit of step {step} timed out after "
                    f"{self._commit_timeout:.0f}s waiting for shard "
                    f"markers from ranks {missing}; leaving {tmp} for GC")
            time.sleep(0.05)
        leaves = []
        for rec in plan.leaves:
            rec = dict(rec)
            if rec["kind"] not in _INLINE_KINDS:
                rec["shards"] = []
            leaves.append(rec)
        files = {}
        for r, meta in sorted(metas.items()):
            files[fmt.shard_npz(r)] = meta["sha256"]
            for e in meta["entries"]:
                leaves[e["leaf"]]["shards"].append(
                    {"rank": r, "key": e["key"], "index": e["index"]})
        manifest = {"version": fmt.SPEC_VERSION, "step": step,
                    "world_size": self._world, "created": time.time(),
                    "treedef": plan.treedef, "files": files,
                    "leaves": leaves}
        fmt.commit(self._dir, step, manifest)
        from horovod_tpu.diagnostics.flight_recorder import record_event
        from horovod_tpu.diagnostics.watchdog import notify_progress
        record_event("ckpt_commit", step=step, world=self._world)
        notify_progress()  # a committed checkpoint IS forward progress

    # ---------------------------------------------------------- restore

    def latest_step(self) -> Optional[int]:
        steps = fmt.list_steps(self._dir)
        return steps[-1] if steps else None

    def all_steps(self) -> List[int]:
        return fmt.list_steps(self._dir)

    def restore_latest(self, like: Any = None,
                       return_step: bool = False) -> Any:
        """Restore the newest committed step — falling back to the next
        older commit when the newest fails verification (sha256
        mismatch, missing shard, corrupt manifest).  A corrupt NEWEST
        checkpoint beside an intact older one used to fail the restore
        outright, turning one bad write into a dead run; now it costs
        the steps between the two commits, counted LOUDLY
        (``hvd_checkpoint_restore_fallback_total``, an error log and a
        ``ckpt_restore_fallback`` flight event per skipped step).

        ``return_step=True`` returns ``(step, state)`` instead — the
        step ACTUALLY restored, which on a fallback is older than
        ``latest_step()``.  Callers that version what they serve by it
        (the serving hot-swap path) must use this form: labeling
        fallback state with ``latest_step()`` would misname the data
        AND permanently mask the newer step."""
        steps = fmt.list_steps(self._dir)
        if not steps:
            self._warn_if_foreign_layout()
            return (None, None) if return_step else None
        for i, step in enumerate(reversed(steps)):
            try:
                state = self.restore(step, like)
                return (step, state) if return_step else state
            except CheckpointError as e:
                if i == len(steps) - 1:
                    raise  # the oldest commit: nothing left to fall to
                older = steps[len(steps) - 2 - i]
                ckpt_metrics.record_restore_fallback()
                get_logger().error(
                    "checkpoint step %d under %s failed verification "
                    "(%s); FALLING BACK to older committed step %d — "
                    "training resumes with the steps in between lost",
                    step, self._dir, e, older)
                from horovod_tpu.diagnostics.flight_recorder import (
                    record_event)
                record_event("ckpt_restore_fallback", step=step,
                             fallback_step=older, error=str(e)[:200])
        return None  # unreachable; loop raises or returns

    def _warn_if_foreign_layout(self) -> None:
        """Nothing restorable, but the directory isn't empty: most
        likely checkpoints from the old orbax default (plain numeric
        step dirs).  Restarting from scratch silently would throw away
        a run's progress — say so once."""
        try:
            foreign = [n for n in os.listdir(self._dir)
                       if n.isdigit() and
                       os.path.isdir(os.path.join(self._dir, n))]
        except OSError:
            return
        if foreign:
            get_logger().warning(
                "checkpoint dir %s holds no native checkpoints but has "
                "step dirs %s in another layout (orbax?): the native "
                "store cannot read them — restore with "
                "horovod_tpu.train.checkpoint.OrbaxCheckpointer and "
                "re-save, or point the store at a fresh directory",
                self._dir, sorted(foreign)[:4])

    def restore(self, step: int, like: Any = None) -> Any:
        """Reassemble global state from the manifest's shard map.  With
        ``like`` (a pytree of arrays or ``ShapeDtypeStruct`` with
        shardings), each array is placed onto the current mesh — the
        elastic resharding path; the checkpoint's world size is
        irrelevant here.  Without ``like``, host (numpy) state in the
        saved structure is returned."""
        t0 = time.monotonic()
        step = int(step)
        manifest = fmt.read_manifest(self._dir, step)
        sdir = fmt.step_dir(self._dir, step)
        cache: Dict[int, Any] = {}
        nbytes = [0]

        def rank_payload(r: int):
            if r not in cache:
                name = fmt.shard_npz(r)
                path = os.path.join(sdir, name)
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError as e:
                    raise CheckpointError(
                        f"missing shard file {path} for committed step "
                        f"{step}") from e
                if self._verify:
                    expect = manifest.get("files", {}).get(name)
                    got = hashlib.sha256(data).hexdigest()
                    if expect is not None and got != expect:
                        raise CheckpointError(
                            f"sha256 mismatch for {path}: manifest "
                            f"{expect[:12]}…, file {got[:12]}…")
                nbytes[0] += len(data)
                cache[r] = np.load(io.BytesIO(data), allow_pickle=False)
            return cache[r]

        values = [self._restore_leaf(rec, rank_payload, step)
                  for rec in manifest["leaves"]]
        out = self._rebuild(manifest, values, like, step)
        ckpt_metrics.record_restore(nbytes[0], time.monotonic() - t0, step)
        from horovod_tpu.diagnostics.flight_recorder import record_event
        from horovod_tpu.diagnostics.watchdog import notify_progress
        record_event("ckpt_restore", step=step, bytes=nbytes[0])
        # a long restore before step 1 must not read as a hang
        notify_progress()
        try:
            from horovod_tpu.metrics import goodput
            goodput.note_checkpoint_stall(time.monotonic() - t0)
        except Exception:
            pass
        return out

    def _restore_leaf(self, rec: dict, rank_payload, step: int) -> Any:
        kind = rec["kind"]
        if kind in _INLINE_KINDS:
            return {"bool": bool, "int": int, "float": float,
                    "str": str}[kind](rec["value"])
        shards = rec.get("shards", [])
        if kind == "pickle":
            if len(shards) != 1:
                raise CheckpointError(
                    f"step {step}: pickled leaf {rec['path']!r} has "
                    f"{len(shards)} shards, expected 1")
            s = shards[0]
            raw = np.asarray(rank_payload(s["rank"])[s["key"]])
            return pickle.loads(raw.tobytes())
        if kind != "array":
            raise CheckpointError(
                f"step {step}: unknown leaf kind {kind!r} for "
                f"{rec['path']!r}")
        shape = tuple(int(d) for d in rec["shape"])
        dtype = fmt.np_dtype(rec["dtype"])
        out = np.empty(shape, dtype)
        covered = 0
        for s in shards:
            data = fmt.logical_view(
                np.asarray(rank_payload(s["rank"])[s["key"]]), rec["dtype"])
            if shape == ():
                out = data.reshape(())
                covered = 1
                continue
            sl = fmt.index_slices(s["index"])
            out[sl] = data
            covered += int(np.prod([e - b for b, e in s["index"]]))
        expect = 1 if shape == () else int(np.prod(shape))
        if covered < expect:
            raise CheckpointError(
                f"step {step}: leaf {rec['path']!r} is missing shards "
                f"({covered}/{expect} elements present)")
        return out[()] if rec.get("scalar") else out

    def _rebuild(self, manifest: dict, values: List[Any], like: Any,
                 step: int) -> Any:
        import jax.tree_util as jtu
        if like is not None:
            flat, treedef = jtu.tree_flatten_with_path(like)
            # match by the serialized parts (stable across jax versions),
            # not keystr's display format
            by_path = {json.dumps(rec["parts"]): v
                       for rec, v in zip(manifest["leaves"], values)}
            out_leaves = []
            for path, lk in flat:
                key = json.dumps(_path_parts(path))
                if key not in by_path:
                    stored = [r["path"] for r in manifest["leaves"][:8]]
                    raise CheckpointError(
                        f"step {step} has no value for {jtu.keystr(path)} "
                        f"(checkpoint holds: {stored}…)")
                out_leaves.append(_place(by_path[key], lk))
            return jtu.tree_unflatten(treedef, out_leaves)
        td64 = manifest.get("treedef")
        if td64:
            try:
                treedef = pickle.loads(base64.b64decode(td64))
                if treedef.num_leaves == len(values):
                    return jtu.tree_unflatten(treedef, values)
            except Exception:
                pass  # structure drift: fall back to recorded paths
        records = [(rec["parts"], v)
                   for rec, v in zip(manifest["leaves"], values)]
        return _rebuild_from_parts(records)

    # --------------------------------------------------------------- gc

    def gc(self, tmp_ttl: Optional[float] = None) -> None:
        """Reclaim old committed steps beyond ``max_to_keep`` and
        abandoned tmp dirs.  A tmp dir is abandoned when its step is
        already committed, or when nothing inside it has been touched
        for ``tmp_ttl`` seconds (default: the commit timeout) — an
        actively-writing peer keeps bumping mtimes, a kill -9 victim
        does not."""
        ttl = self._commit_timeout if tmp_ttl is None else float(tmp_ttl)
        steps = fmt.list_steps(self._dir)
        if self._max_to_keep > 0 and len(steps) > self._max_to_keep:
            for s in steps[:-self._max_to_keep]:
                fmt.remove_tree(fmt.step_dir(self._dir, s))
        now = time.time()
        with self._lock:
            inflight = set(self._inflight_steps)
        stale = list(fmt.list_tmp_steps(self._dir)) + \
            list(fmt.list_broken_steps(self._dir))
        for step, path in stale:
            if step in inflight:
                continue
            committed = os.path.isfile(os.path.join(
                fmt.step_dir(self._dir, step), fmt.MANIFEST))
            if committed or now - fmt.newest_mtime(path) >= ttl:
                fmt.remove_tree(path)
                get_logger().info("checkpoint gc: removed abandoned %s",
                                  path)


def _place(value: Any, like_leaf: Any) -> Any:
    """Put a restored host array where ``like``'s leaf says it lives:
    ``sharding``-carrying leaves go onto the current mesh (only the
    addressable pieces materialize on device), plain ``jax.Array`` /
    ``ShapeDtypeStruct`` leaves go to the default device, anything else
    stays host-side."""
    if not isinstance(value, np.ndarray):
        return value
    import jax
    sharding = getattr(like_leaf, "sharding", None)
    if sharding is not None:
        return jax.make_array_from_callback(value.shape, sharding,
                                            lambda idx: value[idx])
    if isinstance(like_leaf, (jax.Array, jax.ShapeDtypeStruct)):
        return jax.device_put(value)
    return value


def _rebuild_from_parts(records: List[Tuple[List[dict], Any]]) -> Any:
    """``like``-less, treedef-less fallback: rebuild nesting from the
    recorded key paths.  Dicts/attrs become dicts, sequences become
    lists (tuple-ness is only preserved by the treedef path)."""
    if len(records) == 1 and not records[0][0]:
        return records[0][1]
    groups: Dict[Any, List[Tuple[List[dict], Any]]] = {}
    seq = True
    for parts, v in records:
        head, rest = parts[0], parts[1:]
        if "i" not in head:
            seq = False
        key = head.get("k", head.get("a", head.get("i")))
        groups.setdefault(key, []).append((rest, v))
    children = {k: _rebuild_from_parts(g) for k, g in groups.items()}
    if seq and all(isinstance(k, int) for k in children):
        size = max(children) + 1
        return [children.get(i) for i in range(size)]
    return children
