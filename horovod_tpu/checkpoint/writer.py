"""Background checkpoint writer: one serializing thread per store.

The device→host snapshot (the consistent cut) happens inline on the
caller's thread; everything that touches the filesystem — npz
serialization, fsync, the rank-0 commit wait — runs here so the train
loop never blocks on disk.  A bounded inflight cap provides backpressure
when saves outrun storage: ``submit`` blocks once ``max_inflight``
snapshots are queued or being written, so host memory holds at most
``max_inflight + 1`` extra copies of the state.

One thread (not a pool) on purpose: jobs for steps N and N+1 must hit
the two-phase commit protocol in order, and a single queue gives that
for free.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional


class AsyncWriter:
    def __init__(self, max_inflight: int = 2,
                 on_inflight: Optional[Callable[[int], None]] = None,
                 name: str = "hvd-ckpt-writer") -> None:
        self._cap = max(1, int(max_inflight))
        self._jobs: "deque[Callable[[], None]]" = deque()
        self._cond = threading.Condition()
        self._busy = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self._running = False  # worker loop alive; guarded by _cond
        self._name = name
        self._on_inflight = on_inflight

    def _inflight_locked(self) -> int:
        return len(self._jobs) + (1 if self._busy else 0)

    def _notify_inflight(self, n: int) -> None:
        if self._on_inflight is not None:
            try:
                self._on_inflight(n)
            except Exception:
                pass  # a metrics hiccup must never fail a checkpoint

    def _raise_pending_locked(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue; blocks while the inflight cap is reached.  Re-raises
        the first error of any PREVIOUS job (an async failure surfaces
        at the next save/wait, never silently)."""
        with self._cond:
            self._raise_pending_locked()
            if self._closed:
                raise RuntimeError("writer is closed")
            while self._inflight_locked() >= self._cap:
                self._cond.wait()
                self._raise_pending_locked()
            self._jobs.append(job)
            n = self._inflight_locked()
            spawn = not self._running
            if spawn:
                # flagged under the SAME lock the worker uses to decide
                # exit, so a drained thread can never strand a fresh job
                self._running = True
            self._cond.notify_all()
        self._notify_inflight(n)
        if spawn:
            threading.Thread(target=self._run, name=self._name,
                             daemon=True).start()

    def check(self) -> None:
        """Re-raise (and clear) a pending async error WITHOUT blocking —
        lets callers attribute a failure to the save that caused it
        before submitting the next one."""
        with self._cond:
            self._raise_pending_locked()

    def wait(self) -> None:
        """Block until everything queued has been written; re-raise any
        async error."""
        with self._cond:
            while self._inflight_locked() > 0:
                self._cond.wait()
            self._raise_pending_locked()

    def close(self, wait: bool = True) -> None:
        if wait:
            self.wait()
        with self._cond:
            self._closed = True
            self._jobs.clear()
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._jobs or self._closed:
                    self._running = False
                    return
                job = self._jobs.popleft()
                self._busy = True
            try:
                # chaos seam (docs/CHAOS.md): an injected io_error raises
                # here and surfaces through the normal async-error path
                # (next submit/wait), a slow_fsync sleeps the writer —
                # exactly where a real flaky/slow disk would bite
                from horovod_tpu import chaos
                chaos.fire("checkpoint.write")
                job()
            except BaseException as e:  # held for the next submit/wait
                with self._cond:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._busy = False
                    n = self._inflight_locked()
                    self._cond.notify_all()
                self._notify_inflight(n)
