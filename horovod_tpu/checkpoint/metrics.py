"""Checkpoint observability on the process-wide metrics registry.

Everything lands in :mod:`horovod_tpu.metrics.registry`'s default
registry, so the per-worker ``/metrics`` exporter and
``hvd.metrics_snapshot()`` pick it up with no extra wiring
(docs/OBSERVABILITY.md "Checkpoint metrics"):

* ``hvd_checkpoint_save_bytes_total`` — payload bytes THIS rank
  serialized (its shards only, not the global state),
* ``hvd_checkpoint_restore_bytes_total`` — bytes read reassembling
  global arrays at restore,
* ``hvd_checkpoint_save_seconds`` / ``hvd_checkpoint_restore_seconds``
  — histograms; save time is the background write (serialize + fsync +
  rank-0 commit wait), NOT the inline device→host snapshot,
* ``hvd_checkpoint_inflight`` — async saves queued or being written,
* ``hvd_checkpoint_last_step`` — last step this rank committed or
  restored (gauge, merged as ``max``),
* ``hvd_checkpoint_failures_total`` — saves/commits that errored,
* ``hvd_checkpoint_restore_fallback_total`` — restores that skipped a
  corrupt newest checkpoint for the next-older committed step
  (``ShardedCheckpointer.restore_latest``).

Instruments register lazily on first use so workers that never
checkpoint export nothing.
"""

from __future__ import annotations

from typing import Optional, Tuple

from horovod_tpu.metrics.registry import default_registry

_INSTRUMENTS: Optional[Tuple] = None


def _instruments():
    global _INSTRUMENTS
    if _INSTRUMENTS is None:
        reg = default_registry()
        _INSTRUMENTS = (
            reg.counter("hvd_checkpoint_save_bytes_total",
                        help="checkpoint shard bytes written by this rank"),
            reg.counter("hvd_checkpoint_restore_bytes_total",
                        help="checkpoint bytes read at restore"),
            reg.histogram("hvd_checkpoint_save_seconds",
                          help="background shard write + commit wall time"),
            reg.histogram("hvd_checkpoint_restore_seconds",
                          help="restore wall time (read + reassemble)"),
            reg.gauge("hvd_checkpoint_inflight",
                      help="async checkpoint saves not yet on disk",
                      agg="max"),
            reg.gauge("hvd_checkpoint_last_step",
                      help="last checkpoint step committed or restored",
                      agg="max"),
            reg.counter("hvd_checkpoint_failures_total",
                        help="checkpoint saves that failed to commit"),
            reg.counter("hvd_checkpoint_restore_fallback_total",
                        help="restores that skipped a corrupt newest "
                             "checkpoint for an older committed step"),
        )
    return _INSTRUMENTS


def record_save(nbytes: int, seconds: float, step: int) -> None:
    save_b, _, save_s, _, _, last = _instruments()[:6]
    save_b.inc(nbytes)
    save_s.observe(seconds)
    last.set(step)


def record_restore(nbytes: int, seconds: float, step: int) -> None:
    _, rest_b, _, rest_s, _, last = _instruments()[:6]
    rest_b.inc(nbytes)
    rest_s.observe(seconds)
    last.set(step)


def record_failure() -> None:
    _instruments()[6].inc()


def record_restore_fallback() -> None:
    _instruments()[7].inc()


def set_inflight(n: int) -> None:
    _instruments()[4].set(n)
