"""Deterministic fault injection: provoke the failures the stack claims
to survive, on every CI run.

The complement of the flight recorder / hang autopsy (PR 4): diagnostics
explain a failure after the fact; the chaos harness CAUSES the failures
— socket stalls, KV blackouts, checkpoint IO errors, rank kills — on a
seeded, reproducible schedule, so the elastic + durable-checkpoint
recovery path is exercised instead of trusted.  Reference analog: none
(the reference's fault coverage is hand-written per-test exits);
1802.05799's pitch that a dying worker is a recoverable event is exactly
what this subsystem regression-tests.

Usage: set ``HVD_TPU_FAULT_PLAN`` (inline JSON or a file path; schema in
:mod:`horovod_tpu.chaos.plan` and docs/CHAOS.md) and run normally.
``hvd.init()`` arms the plan; instrumented call sites fire their seams
through :func:`fire`; ``transport.*`` rules are compiled into the C++
core's env-read injection points.  Every injected fault is stamped into
the flight recorder (``fault_injected`` events) and counted on
``/metrics`` (``hvd_chaos_injected_total{seam=,kind=}``).

With no plan set the seams are dead: :func:`fire` is a module-global
None check and the C++ transport path is a single null-pointer test per
frame — nothing allocates, nothing sleeps, nothing logs.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Tuple

from horovod_tpu.chaos.plan import (FaultPlan, FaultPlanError, FaultRule,
                                    SEAMS, compile_transport_spec,
                                    load_plan_from_env, parse_plan)

__all__ = ["install", "uninstall", "active", "fire", "step_tick",
           "grad_injection", "grad_rules_armed", "GRAD_CODES",
           "engine", "ChaosEngine", "FaultPlan", "FaultPlanError",
           "FaultRule", "SEAMS", "parse_plan"]

TRANSPORT_ENV = "HVD_TPU_CHAOS_TRANSPORT"


class ChaosEngine:
    """Per-process injector: tracks per-seam invocation counters and
    per-rule fire counts, applies Python-seam faults."""

    def __init__(self, plan: FaultPlan, rank: int) -> None:
        self.plan = plan
        self.rank = rank
        self._lock = threading.Lock()
        self._invocations = {}   # seam -> next auto index
        self._fired = {}         # rule.index -> fires so far
        self.injected_total = 0

    # -- schedule -----------------------------------------------------------
    def _next_index(self, seam: str) -> int:
        with self._lock:
            i = self._invocations.get(seam, 0)
            self._invocations[seam] = i + 1
            return i

    def _should_fire(self, rule: FaultRule, invocation: int) -> bool:
        if not rule.decides_fire(self.plan.seed, invocation):
            return False
        with self._lock:
            fired = self._fired.get(rule.index, 0)
            if rule.count and fired >= rule.count:
                return False
            self._fired[rule.index] = fired + 1
        if rule.marker:
            # at-most-once across restarts: O_EXCL create is the gate, so
            # a replacement process (or a racing thread) cannot re-fire.
            # {rank} expands per firing rank — a correlated multi-rank
            # rule kills every group member once each, rather than the
            # first member's marker disarming the rest of the group.
            marker = rule.marker.replace("{rank}", str(self.rank))
            try:
                fd = os.open(marker,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
            except FileExistsError:
                return False
            except OSError as e:
                # fire anyway, but SAY the at-most-once guarantee is
                # gone — under an elastic driver an unwritable marker
                # turns a one-shot kill into a kill-every-replacement
                # livelock, and that must read as a config error
                try:
                    from horovod_tpu.common.logging import get_logger
                    get_logger().error(
                        "chaos: marker %r for rule #%d is unwritable "
                        "(%s); the rule is NO LONGER at-most-once "
                        "across restarts", rule.marker, rule.index, e)
                except Exception:
                    pass
        return True

    # -- firing -------------------------------------------------------------
    def fire(self, seam: str, index: Optional[int] = None,
             peer=None) -> List[Tuple[str, str]]:
        """Evaluate ``seam`` at ``index`` (auto-incrementing per-seam
        counter when None).  Applies every matching rule's fault —
        delays sleep in place, error kinds RAISE, kill/exit terminate
        the process, pure-signal kinds (``preemption``/``notice``, the
        ``grad`` corruption kinds) only report.  ``peer`` names the
        request's TARGET for the ``kv.partition`` seam (a worker rank or
        ``"driver"``); rules whose cut the (self rank, peer) pair
        crosses fire bidirectionally.  Returns the (seam, kind) pairs
        applied, for tests and signal-kind consumers."""
        return [(r.seam, r.kind)
                for r in self.fire_rules(seam, index=index, peer=peer)]

    def fire_rules(self, seam: str, index: Optional[int] = None,
                   peer=None) -> List[FaultRule]:
        """:meth:`fire`, but returning the applied RULES — consumers
        that need a rule's parameters (the grad ``scale`` kind's
        ``factor``) read them off the rule instead of a string pair."""
        invocation = self._next_index(seam) if index is None else index
        applied: List[FaultRule] = []
        raise_after: Optional[BaseException] = None
        for rule in self.plan.rules_for(seam, self.rank):
            if rule.groups is not None and \
                    not rule.matches_pair(self.rank, peer):
                continue
            if not self._should_fire(rule, invocation):
                continue
            self._note(rule, invocation)
            applied.append(rule)
            if rule.kind in ("delay", "slow_fsync"):
                time.sleep(rule.delay_ms / 1000.0)
            elif rule.kind == "stall":
                time.sleep(rule.stall_s)
            elif rule.kind == "error":
                raise_after = ConnectionResetError(
                    f"chaos: injected connection reset ({seam} "
                    f"invocation {invocation})")
            elif rule.kind == "blackout":
                raise_after = ConnectionRefusedError(
                    f"chaos: injected blackout ({seam} invocation "
                    f"{invocation})")
            elif rule.kind == "partition":
                raise_after = ConnectionRefusedError(
                    f"chaos: injected partition (rank {self.rank} -> "
                    f"{peer}, invocation {invocation})")
            elif rule.kind in ("notice", "nan", "inf", "scale", "shed"):
                pass  # pure signal: the applied list IS the payload
                # (grad kinds are consumed in-graph by train/guard.py;
                # the serving `shed` kind by the replica's /infer
                # handler, which maps it to an explicit 429 — serving
                # `error` takes the raising `error` branch above and
                # surfaces as the handler's 500)
            elif rule.kind == "io_error":
                raise_after = OSError(
                    f"chaos: injected IO error ({seam} invocation "
                    f"{invocation})")
            elif rule.kind == "kill":
                self._flush_flight("kill")
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            elif rule.kind == "exit":
                self._flush_flight("exit")
                os._exit(rule.exit_code)
        if raise_after is not None:
            raise raise_after
        return applied

    # -- bookkeeping --------------------------------------------------------
    def _note(self, rule: FaultRule, invocation: int) -> None:
        with self._lock:  # seams fire from many threads (kv listener,
            self.injected_total += 1  # checkpoint writer, train loop)
        try:
            from horovod_tpu.diagnostics.flight_recorder import record_event
            # "fault", not "kind": the ring's own event-kind key wins
            record_event("fault_injected", seam=rule.seam, fault=rule.kind,
                         rule=rule.index, invocation=invocation,
                         rank=self.rank)
        except Exception:
            pass
        try:
            from horovod_tpu.metrics.registry import default_registry
            default_registry().counter(
                "hvd_chaos_injected_total",
                help="faults injected by the chaos harness, per seam/kind",
                labels={"seam": rule.seam, "kind": rule.kind}).inc()
        except Exception:
            pass
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "chaos: injecting %s/%s (rule #%d, invocation %d)",
                rule.seam, rule.kind, rule.index, invocation)
        except Exception:
            pass

    def _flush_flight(self, why: str) -> None:
        """A kill/exit fault destroys the process before anything can ask
        for evidence — dump the flight ring to the autopsy dir first so
        the soak test (and a real post-mortem) still sees the injection."""
        try:
            from horovod_tpu.diagnostics.flight_recorder import (
                crash_dump_path, record_event, recorder)
            record_event("chaos_terminating", fault=why, rank=self.rank)
            recorder().dump_to(crash_dump_path())
        except Exception:
            pass


_engine: Optional[ChaosEngine] = None
_lock = threading.Lock()
_we_set_transport_env = False
_armed_key = None  # (rank, plan env, seed env) the engine was built for


def _env_rank() -> int:
    v = os.environ.get("HVD_TPU_RANK", os.environ.get("HOROVOD_RANK", "0"))
    try:
        return int(v)
    except ValueError:
        return 0


def install(rank: Optional[int] = None) -> Optional[ChaosEngine]:
    """(Re-)arm the fault plan from env for this process.  Called by
    ``hvd.init()`` on every (re-)initialization — an elastic re-mesh can
    renumber this worker, and rank-scoped rules plus the compiled
    transport spec must follow the NEW rank.  No plan in env = everything
    disarmed (and a previously compiled transport spec cleared).

    Must run before the native core boots: the C++ transport reads its
    compiled spec from ``HVD_TPU_CHAOS_TRANSPORT`` at ``Transport::Init``.
    """
    global _engine, _we_set_transport_env, _armed_key
    with _lock:
        raw = os.environ.get("HVD_TPU_FAULT_PLAN", "").strip()
        seed_raw = os.environ.get("HVD_TPU_FAULT_SEED", "").strip()
        if not raw:
            _engine = None
            _armed_key = None
            if _we_set_transport_env:
                os.environ.pop(TRANSPORT_ENV, None)
                _we_set_transport_env = False
            return None
        r = _env_rank() if rank is None else int(rank)
        if _engine is not None and _armed_key == (r, raw, seed_raw):
            # same rank, same plan: keep the armed engine and its
            # invocation counters (hvd.init() and a raw CoreBackend()
            # both install; re-arming here would replay every window)
            return _engine
        plan = load_plan_from_env()  # FaultPlanError propagates: a typo'd
        # plan must fail the job loudly, not run fault-free
        if plan is None or not plan.rules:
            _engine = None
            _armed_key = None
            if _we_set_transport_env:
                os.environ.pop(TRANSPORT_ENV, None)
                _we_set_transport_env = False
            return None
        _engine = ChaosEngine(plan, r)
        _armed_key = (r, raw, seed_raw)
        spec = compile_transport_spec(plan, r)
        if spec:
            os.environ[TRANSPORT_ENV] = spec
            _we_set_transport_env = True
        elif _we_set_transport_env:
            os.environ.pop(TRANSPORT_ENV, None)
            _we_set_transport_env = False
        try:
            from horovod_tpu.diagnostics.flight_recorder import record_event
            record_event("chaos_armed", rank=r, seed=plan.seed,
                         rules=len(plan.rules),
                         transport_spec=spec or None)
        except Exception:
            pass
        try:
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "chaos: armed %d fault rule(s), seed=%d, rank=%d%s",
                len(plan.rules), plan.seed, r,
                f", transport spec: {spec}" if spec else "")
        except Exception:
            pass
        return _engine


def uninstall() -> None:
    """Disarm everything (tests)."""
    global _engine, _we_set_transport_env, _armed_key
    with _lock:
        _engine = None
        _armed_key = None
        if _we_set_transport_env:
            os.environ.pop(TRANSPORT_ENV, None)
            _we_set_transport_env = False


def active() -> bool:
    return _engine is not None


def engine() -> Optional[ChaosEngine]:
    return _engine


def fire(seam: str, index: Optional[int] = None,
         peer=None) -> List[Tuple[str, str]]:
    """Fire a seam if a plan is armed; the no-plan fast path is one
    module-global None check (the instrumented call sites stay free when
    chaos is off).  ``peer`` carries the request target for the
    ``kv.partition`` seam."""
    eng = _engine
    if eng is None:
        return ()
    return eng.fire(seam, index=index, peer=peer)


def step_tick(step: int) -> List[Tuple[str, str]]:
    """The ``step`` seam: call once per training step with the step
    number (rank kill/stall schedules key on it).  Wired into
    ``TelemetryCallback.on_step_begin``; custom loops call it directly."""
    eng = _engine
    if eng is None:
        return ()
    return eng.fire("step", index=int(step))


#: grad-seam kind -> the in-graph injection code train/guard.py applies
#: (0 = clean; the float travels into the compiled step as data, so a
#: firing window never triggers a recompile)
GRAD_CODES = {"nan": 1, "inf": 2, "scale": 3}


def grad_rules_armed() -> bool:
    """Does the armed plan carry any ``grad`` rules for THIS rank?  The
    train-step factories consult this at build time: only then is the
    injection seam compiled into the step (zero cost otherwise)."""
    eng = _engine
    return bool(eng is not None
                and eng.plan.rules_for("grad", eng.rank))


def grad_injection(step: int) -> Tuple[int, float]:
    """Evaluate the ``grad`` seam at training step ``step``; returns
    ``(code, factor)`` — the :data:`GRAD_CODES` code of the first
    applied rule (0 when clean) and its ``scale`` factor (0.0 for
    nan/inf).  Counted/flight-recorded like every other injection."""
    eng = _engine
    if eng is None:
        return (0, 0.0)
    for rule in eng.fire_rules("grad", index=int(step)):
        return (GRAD_CODES[rule.kind],
                float(rule.factor) if rule.kind == "scale" else 0.0)
    return (0, 0.0)
