"""Declarative fault plans: schema, validation, deterministic schedules.

A fault plan is a JSON document (inline in ``HVD_TPU_FAULT_PLAN`` or a
path to a file) describing WHICH seams misbehave, WHEN, and HOW:

.. code-block:: json

    {
      "seed": 7,
      "faults": [
        {"seam": "step", "kind": "kill", "rank": 2, "start": 3,
         "count": 1, "marker": "/tmp/job/killed_once"},
        {"seam": "kv.request", "kind": "blackout", "start": 2, "stop": 6},
        {"seam": "transport.recv", "kind": "delay", "rank": 1, "peer": 0,
         "start": 10, "count": 20, "delay_ms": 30},
        {"seam": "checkpoint.write", "kind": "io_error", "rank": 0,
         "start": 1, "count": 1}
      ]
    }

Rule fields:

* ``seam`` (required) — one of the :data:`SEAMS` catalog below.
* ``kind`` (required) — the fault flavor, validated per seam.
* ``rank`` — int, list of ints, or ``"*"`` (default): which ranks arm
  this rule.  Matched against the worker's launched rank at install time
  (re-evaluated on elastic re-init, when ranks can renumber).
* ``start`` / ``stop`` — half-open invocation window ``[start, stop)``
  over the seam's 0-based invocation index (for the ``step`` seam the
  index IS the training step the caller passes).  Defaults: whole run.
* ``count`` — at most this many fires per process (0 = unlimited).
* ``probability`` — per-invocation chance in ``(0, 1]``; the draw is a
  pure function of ``(seed, rule, index)``, so the same plan + seed
  yields the same fire schedule on every run and every rank.
* ``marker`` — optional filesystem path making the rule at-most-once
  ACROSS process restarts: a rule whose marker file exists is disarmed,
  and firing creates it.  Without this, a ``step``-seam ``kill`` under an
  elastic driver would kill every replacement at the same step forever.
  A ``{rank}`` placeholder expands to the firing rank — one correlated
  multi-host rule (``"rank": [2, 3]``) then takes out EVERY rank of the
  group exactly once each, instead of the first kill's marker disarming
  the rest of the group.
* ``groups`` — ``kv.partition`` only: the two sides of the cut, each a
  list of worker ranks (the literal ``"driver"`` names the root KV
  server).  A KV request whose sender and target fall on opposite sides
  is refused, in both directions, for the rule's window.
* kind parameters: ``delay_ms`` (delay/slow kinds), ``peer``
  (transport kinds; int or ``"*"``), ``stall_s`` (step stall),
  ``exit_code`` (step exit).

Validation is strict — a typo'd seam name or an overlapping window is a
config error surfaced at install time, not a silently dead fault.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
from typing import Any, Dict, List, Optional, Sequence, Union

#: seam -> allowed fault kinds.  Python-injected seams are fired by the
#: instrumented call sites (see docs/CHAOS.md for the catalog semantics);
#: ``transport.*`` seams are compiled to the C++ core's
#: ``HVD_TPU_CHAOS_TRANSPORT`` env spec at install time.
SEAMS: Dict[str, frozenset] = {
    "kv.request": frozenset({"error", "blackout", "delay"}),
    # network partition between HOST GROUPS: a KV request whose sender
    # and target fall on opposite sides of the cut is refused, in BOTH
    # directions.  Fired by the KV clients with the target's identity
    # (a worker rank for relay hops, the literal "driver" for the root
    # KV) — see docs/CHAOS.md.
    "kv.partition": frozenset({"partition"}),
    "checkpoint.write": frozenset({"io_error", "slow_fsync"}),
    "step": frozenset({"kill", "stall", "exit"}),
    # advance preemption notice (the TPU maintenance-event analog):
    # non-destructive — the PreemptionWatcher polls this seam and treats
    # a fire as "this host is doomed", driving the proactive drain path
    # (docs/ELASTIC.md "Proactive drain & preemption").
    "preemption": frozenset({"notice"}),
    "transport.send": frozenset({"delay", "drop", "close", "bit_flip"}),
    "transport.recv": frozenset({"delay", "drop", "close", "bit_flip"}),
    # serving request path (docs/CHAOS.md, docs/SERVING.md): fired by
    # the replica's /infer handler per request — ``error`` fails the
    # request with 500 (the router must retry it to a survivor),
    # ``delay`` sleeps in the handler (the router's hedge must cover
    # it), ``shed`` forces an explicit 429 (backpressure must surface,
    # never silently drop).  Invocation index = per-process request
    # count.
    "serving.request": frozenset({"error", "delay", "shed"}),
    # KV page-pool starvation (docs/CHAOS.md): fired by the generate
    # engine's page pool per allocation attempt — ``starve`` makes the
    # pool refuse the grant as if it could not cover the request, so
    # admitted traffic piles up in ``page_wait`` (the request ledger
    # must attribute it there and the ``kv_thrash`` detector must name
    # it).  Invocation index = per-process allocation attempt count.
    "serving.kv": frozenset({"starve"}),
    # gradient corruption at the train step (docs/CHAOS.md): the seam
    # index IS the training step (like ``step``); the armed kinds are
    # read by the guard-integrated train-step factories
    # (horovod_tpu/train/guard.py) and applied IN-GRAPH to the step's
    # gradients — ``nan``/``inf`` poison them (the numeric guardrail
    # must skip the step), ``scale`` multiplies them by ``factor``
    # (a finite SDC stand-in the guard cannot see but the cross-replica
    # canary must).  Pure signal at the seam: nothing raises here.
    "grad": frozenset({"nan", "inf", "scale"}),
    # the elastic DRIVER process (docs/CHAOS.md, docs/ELASTIC.md "Driver
    # failover & takeover"): fired by the driver's own poll loop, one
    # invocation per poll tick — ``kill``/``exit`` terminate the control
    # plane mid-flight (the launcher's supervisor respawns it into a
    # journal takeover), ``stall`` freezes the poll loop so workers must
    # ride the outage out under HVD_TPU_DRIVER_OUTAGE_GRACE_S.  Driver
    # rules should leave ``rank`` unset (the driver is not a worker —
    # only the wildcard matches it) and use ``marker`` for at-most-once
    # across supervisor respawns.
    "driver": frozenset({"kill", "stall", "exit"}),
}

_UNBOUNDED = 2 ** 62


class FaultPlanError(ValueError):
    """A fault plan failed validation (bad seam/kind, malformed window,
    overlapping windows for the same seam+kind, ...)."""


@dataclasses.dataclass
class FaultRule:
    seam: str
    kind: str
    ranks: Optional[frozenset] = None   # None = all ranks
    start: int = 0
    stop: int = _UNBOUNDED              # half-open [start, stop)
    count: int = 0                      # max fires per process; 0 = inf
    probability: float = 1.0
    delay_ms: float = 0.0
    peer: int = -1                      # transport seams; -1 = any peer
    stall_s: float = 0.0
    exit_code: int = 1
    # transport bit_flip only: frames under this payload size are immune
    # — flips target tensor DATA frames, not the small lockstep
    # negotiation frames whose per-peer index is timing-dependent
    min_bytes: int = 0
    # grad scale only: the multiplicative spike applied to the rank's
    # gradients while the rule fires
    factor: float = 0.0
    marker: str = ""
    # kv.partition only: the two sides of the cut.  Members are worker
    # ranks (ints) or the literal "driver" (the root KV server).
    groups: Optional[tuple] = None      # (frozenset, frozenset)
    index: int = 0                      # position in the plan (rule id)

    def matches_rank(self, rank: int) -> bool:
        return self.ranks is None or rank in self.ranks

    def matches_pair(self, rank, peer) -> bool:
        """kv.partition: does (sender ``rank``, target ``peer``) cross
        the cut?  Bidirectional by construction."""
        if self.groups is None or peer is None:
            return False
        a, b = self.groups
        return (rank in a and peer in b) or (rank in b and peer in a)

    def in_window(self, invocation: int) -> bool:
        return self.start <= invocation < self.stop

    def decides_fire(self, seed: int, invocation: int) -> bool:
        """Pure function of (seed, rule identity, invocation): same plan +
        seed => same schedule, regardless of which process asks."""
        if not self.in_window(invocation):
            return False
        if self.probability >= 1.0:
            return True
        key = f"{seed}:{self.index}:{self.seam}:{self.kind}:{invocation}"
        return random.Random(key).random() < self.probability


@dataclasses.dataclass
class FaultPlan:
    seed: int = 0
    rules: List[FaultRule] = dataclasses.field(default_factory=list)

    def rules_for(self, seam: str, rank: int) -> List[FaultRule]:
        return [r for r in self.rules
                if r.seam == seam and r.matches_rank(rank)]


def _parse_ranks(v: Any) -> Optional[frozenset]:
    if v is None or v == "*":
        return None
    if isinstance(v, bool):
        raise FaultPlanError(f"bad rank spec {v!r}")
    if isinstance(v, int):
        return frozenset({v})
    if isinstance(v, (list, tuple)):
        try:
            return frozenset(int(x) for x in v)
        except (TypeError, ValueError):
            raise FaultPlanError(f"bad rank list {v!r}") from None
    raise FaultPlanError(f"bad rank spec {v!r} (int, list, or '*')")


_RULE_KEYS = {"seam", "kind", "rank", "start", "stop", "count",
              "probability", "delay_ms", "peer", "stall_s", "exit_code",
              "marker", "groups", "min_bytes", "factor"}


def _parse_groups(v: Any, index: int) -> tuple:
    """kv.partition ``groups``: exactly two disjoint, non-empty sides;
    members are ints (worker ranks) or the literal ``"driver"``."""
    if not (isinstance(v, (list, tuple)) and len(v) == 2):
        raise FaultPlanError(
            f"fault #{index}: 'groups' must be a list of exactly two "
            "host groups")
    sides = []
    for side in v:
        if not isinstance(side, (list, tuple)) or not side:
            raise FaultPlanError(
                f"fault #{index}: each partition group must be a "
                "non-empty list")
        members = set()
        for m in side:
            if m == "driver":
                members.add("driver")
            elif isinstance(m, bool) or not isinstance(m, int):
                raise FaultPlanError(
                    f"fault #{index}: bad group member {m!r} (worker "
                    "rank int or 'driver')")
            else:
                members.add(m)
        sides.append(frozenset(members))
    if sides[0] & sides[1]:
        raise FaultPlanError(
            f"fault #{index}: partition groups overlap "
            f"({sorted(map(str, sides[0] & sides[1]))}) — a member "
            "cannot sit on both sides of the cut")
    return (sides[0], sides[1])


def _parse_rule(doc: Dict[str, Any], index: int) -> FaultRule:
    if not isinstance(doc, dict):
        raise FaultPlanError(f"fault #{index}: not an object: {doc!r}")
    unknown = set(doc) - _RULE_KEYS
    if unknown:
        raise FaultPlanError(
            f"fault #{index}: unknown keys {sorted(unknown)}")
    seam = doc.get("seam")
    if seam not in SEAMS:
        raise FaultPlanError(
            f"fault #{index}: unknown seam {seam!r} "
            f"(known: {sorted(SEAMS)})")
    kind = doc.get("kind")
    if kind not in SEAMS[seam]:
        raise FaultPlanError(
            f"fault #{index}: kind {kind!r} not valid for seam {seam!r} "
            f"(valid: {sorted(SEAMS[seam])})")
    try:
        start = int(doc.get("start", 0))
        count = int(doc.get("count", 0))
        stop = doc.get("stop")
        stop = _UNBOUNDED if stop is None else int(stop)
        probability = float(doc.get("probability", 1.0))
        delay_ms = float(doc.get("delay_ms", 0.0))
        stall_s = float(doc.get("stall_s", 0.0))
        exit_code = int(doc.get("exit_code", 1))
        peer = doc.get("peer", -1)
        peer = -1 if peer in ("*", None) else int(peer)
        min_bytes = int(doc.get("min_bytes", 0))
        factor = float(doc.get("factor", 0.0))
    except (TypeError, ValueError) as e:
        raise FaultPlanError(f"fault #{index}: bad field value: {e}") \
            from None
    if start < 0 or stop <= start:
        raise FaultPlanError(
            f"fault #{index}: window [{start}, "
            f"{stop if stop != _UNBOUNDED else 'inf'}) is empty or "
            "negative")
    if count < 0:
        raise FaultPlanError(f"fault #{index}: count must be >= 0")
    if not (0.0 < probability <= 1.0):
        raise FaultPlanError(
            f"fault #{index}: probability must be in (0, 1]")
    if delay_ms < 0 or stall_s < 0:
        raise FaultPlanError(f"fault #{index}: negative delay")
    marker = str(doc.get("marker", ""))
    if marker and seam.startswith("transport."):
        # the C++ injector has no marker support; accepting one would
        # silently re-arm the fault in every restarted process — the
        # exact hazard marker exists to prevent
        raise FaultPlanError(
            f"fault #{index}: 'marker' is not supported on transport "
            "seams (the C++ injector is stateless across restarts); "
            "bound the fault with start/stop/count instead")
    if kind in ("delay", "slow_fsync") and delay_ms <= 0:
        raise FaultPlanError(
            f"fault #{index}: kind {kind!r} needs delay_ms > 0 "
            "(a zero-length delay would count as injected while "
            "exercising nothing)")
    if kind == "stall" and stall_s <= 0:
        raise FaultPlanError(
            f"fault #{index}: kind 'stall' needs stall_s > 0")
    if min_bytes < 0:
        raise FaultPlanError(f"fault #{index}: min_bytes must be >= 0")
    if min_bytes and kind != "bit_flip":
        raise FaultPlanError(
            f"fault #{index}: 'min_bytes' is only valid for transport "
            "bit_flip rules (the payload-size gate that keeps flips off "
            "the small negotiation frames)")
    if kind == "scale":
        if factor <= 0 or factor == 1.0:
            raise FaultPlanError(
                f"fault #{index}: kind 'scale' needs factor > 0 and "
                "!= 1 (a unit spike would count as injected while "
                "corrupting nothing)")
    elif "factor" in doc:
        raise FaultPlanError(
            f"fault #{index}: 'factor' is only valid for the grad "
            "'scale' kind")
    groups = None
    if seam == "kv.partition":
        if "groups" not in doc:
            raise FaultPlanError(
                f"fault #{index}: kv.partition needs 'groups' — the two "
                "sides of the cut")
        groups = _parse_groups(doc["groups"], index)
    elif "groups" in doc:
        raise FaultPlanError(
            f"fault #{index}: 'groups' is only valid for the "
            "kv.partition seam")
    return FaultRule(seam=seam, kind=kind, ranks=_parse_ranks(
        doc.get("rank", "*")), start=start, stop=stop, count=count,
        probability=probability, delay_ms=delay_ms, peer=peer,
        stall_s=stall_s, exit_code=exit_code, min_bytes=min_bytes,
        factor=factor, marker=marker, groups=groups, index=index)


def _ranks_overlap(a: Optional[frozenset], b: Optional[frozenset]) -> bool:
    if a is None or b is None:
        return True
    return bool(a & b)


def _check_overlaps(rules: Sequence[FaultRule]) -> None:
    """Two rules with the same (seam, kind) firing on overlapping ranks
    over overlapping windows are ambiguous (which one's parameters
    apply?) — reject the plan."""
    def effective_ranks(r: FaultRule) -> Optional[frozenset]:
        # partition rules scope by their groups, not by `rank`: two cuts
        # over disjoint member sets are independent schedules
        if r.groups is not None:
            return r.groups[0] | r.groups[1]
        return r.ranks

    for i, a in enumerate(rules):
        for b in rules[i + 1:]:
            if a.seam != b.seam or a.kind != b.kind:
                continue
            if not _ranks_overlap(effective_ranks(a), effective_ranks(b)):
                continue
            if a.seam.startswith("transport.") and a.peer != b.peer \
                    and a.peer != -1 and b.peer != -1:
                continue  # distinct peers: independent schedules
            if a.start < b.stop and b.start < a.stop:
                raise FaultPlanError(
                    f"faults #{a.index} and #{b.index} ({a.seam}/{a.kind})"
                    f" have overlapping windows [{a.start},{a.stop}) and "
                    f"[{b.start},{b.stop}) on overlapping ranks")


def parse_plan(doc: Union[str, Dict[str, Any]],
               seed_override: Optional[int] = None) -> FaultPlan:
    """Parse + validate a plan from a JSON string or an already-decoded
    dict; raises :class:`FaultPlanError` on any schema violation."""
    if isinstance(doc, str):
        try:
            doc = json.loads(doc)
        except ValueError as e:
            raise FaultPlanError(f"fault plan is not valid JSON: {e}") \
                from None
    if not isinstance(doc, dict):
        raise FaultPlanError(f"fault plan must be an object, got "
                             f"{type(doc).__name__}")
    unknown = set(doc) - {"seed", "faults"}
    if unknown:
        raise FaultPlanError(f"unknown plan keys {sorted(unknown)}")
    faults = doc.get("faults", [])
    if not isinstance(faults, list):
        raise FaultPlanError("'faults' must be a list")
    rules = [_parse_rule(r, i) for i, r in enumerate(faults)]
    _check_overlaps(rules)
    try:
        seed = int(doc.get("seed", 0))
    except (TypeError, ValueError):
        raise FaultPlanError("'seed' must be an integer") from None
    if seed_override is not None:
        seed = seed_override
    return FaultPlan(seed=seed, rules=rules)


def load_plan_from_env() -> Optional[FaultPlan]:
    """The plan named by ``HVD_TPU_FAULT_PLAN`` (inline JSON when the
    value starts with ``{``, else a file path), seed overridden by
    ``HVD_TPU_FAULT_SEED``; None when unset."""
    raw = os.environ.get("HVD_TPU_FAULT_PLAN", "").strip()
    if not raw:
        return None
    if not raw.startswith("{"):
        try:
            with open(raw) as f:
                raw = f.read()
        except OSError as e:
            raise FaultPlanError(
                f"HVD_TPU_FAULT_PLAN names an unreadable file: {e}") \
                from None
    seed_env = os.environ.get("HVD_TPU_FAULT_SEED", "").strip()
    seed_override = None
    if seed_env:
        try:
            seed_override = int(seed_env)
        except ValueError:
            raise FaultPlanError(
                f"HVD_TPU_FAULT_SEED is not an integer: {seed_env!r}") \
                from None
    return parse_plan(raw, seed_override=seed_override)


def compile_transport_spec(plan: FaultPlan, rank: int) -> str:
    """Compile this rank's ``transport.*`` rules into the compact spec the
    C++ core parses from ``HVD_TPU_CHAOS_TRANSPORT`` (rules joined by
    ``;``, fields by ``:``).  Probability is resolved here per-rule into
    the deterministic schedule's parameters; the C++ side applies windows
    and counts only (it has no seeded RNG), so probabilistic transport
    rules are rejected at validation."""
    parts = []
    for r in plan.rules_for("transport.send", rank) + \
            plan.rules_for("transport.recv", rank):
        if r.probability < 1.0:
            raise FaultPlanError(
                f"fault #{r.index}: transport seams do not support "
                "probability < 1 (the C++ injector is window/count based)")
        direction = "recv" if r.seam.endswith("recv") else "send"
        window = 0 if r.stop == _UNBOUNDED else r.stop - r.start
        if r.kind == "bit_flip":
            # bit_flip counts FIRES, not window frames: the plan's
            # ``count`` compiles to the C++ ``fires`` budget (at most N
            # frames ever corrupted) while ``start``/``stop`` stay the
            # frame-index window; ``min_bytes`` keeps the flip off the
            # small (timing-indexed) negotiation frames
            parts.append(
                f"dir={direction}:kind=bit_flip:peer={r.peer}:"
                f"after={r.start}:count={window}:ms=0:"
                f"minb={r.min_bytes}:fires={r.count}")
            continue
        stop_count = r.count
        if window:
            stop_count = min(stop_count, window) if stop_count else window
        parts.append(
            f"dir={direction}:kind={r.kind}:peer={r.peer}:"
            f"after={r.start}:count={stop_count}:ms={r.delay_ms:g}")
    return ";".join(parts)
