"""The unified causal reader: one query across every evidence plane.

Two CLI verbs (``python -m horovod_tpu.diagnostics ...``):

* ``timeline`` — merge flight dumps + per-rank timeline shards + the
  serving request log + the autopilot ``actions_rank<r>.jsonl`` + the
  re-mesh history into ONE skew-corrected Perfetto/chrome trace,
  reusing the shard merger's clock machinery
  (:mod:`horovod_tpu.diagnostics.merge`): each plane becomes a track,
  flight ``trace_span`` records become complete (``X``) spans, stamped
  events become instants, and every flight dump's recorded
  ``wall_offset_s`` maps its events onto the coordinator's clock so
  cross-rank evidence lines up instead of drifting by clock skew.
* ``trace <id>`` — the causal tree of one trace id: every span and
  stamped event carrying the id, joined by span/parent into a tree
  with per-hop latency attribution (each hop's duration, its share of
  the parent, the slow hop flagged).

Record sources understood (all optional — the reader works with
whatever planes exist):

* flight dumps (``hvd_flight_rank<r>.json`` / autopsy
  ``flight_rank<r>.json``): ``trace_span`` events are spans; any other
  event stamped ``trace``/``span`` is a point node;
* serving request logs (JSONL, rotated ``.1`` read first);
* the OBS store (``HVD_TPU_OBS_DIR``): ``actions_rank<r>.jsonl``
  decisions and re-mesh history points stamped with a trace.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

SPAN_KIND = "trace_span"

#: flight-event fields that are span plumbing, not display attributes
_SPAN_FIELDS = ("ts", "seq", "kind", "plane", "name", "start", "dur_s",
                "trace", "span", "parent")


# -- loading ------------------------------------------------------------------
def load_flight_dump(path: str) -> Optional[dict]:
    """One flight dump document, or None when unreadable (one dead
    rank's garbled file must not cost the others' evidence)."""
    try:
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("events"), list):
            return doc
    except (OSError, ValueError):
        pass
    return None


def find_flight_dumps(directory: str) -> List[str]:
    """Flight dumps under ``directory`` (crash hooks, autopsies and the
    acceptance tests all write ``*flight*rank*.json``)."""
    out = [p for p in glob.glob(os.path.join(directory, "*.json"))
           if "flight" in os.path.basename(p).lower()
           and "rank" in os.path.basename(p).lower()]
    return sorted(out)


def read_jsonl(path: str) -> List[dict]:
    """Torn-tail-tolerant JSONL reader, rotated generation first."""
    out: List[dict] = []
    for p in (path + ".1", path):
        try:
            with open(p) as f:
                for line in f:
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue  # torn tail line
                    if isinstance(doc, dict):
                        out.append(doc)
        except OSError:
            continue
    return out


def _obs_files(obs_dir: str, basename: str) -> List[str]:
    try:
        return sorted(
            os.path.join(obs_dir, n) for n in os.listdir(obs_dir)
            if n.startswith(basename + "_rank") and n.endswith(".jsonl"))
    except OSError:
        return []


# -- trace collection ---------------------------------------------------------
def spans_from_events(events: Sequence[dict], offset_s: float = 0.0,
                      source: Optional[str] = None,
                      trace_id: Optional[str] = None
                      ) -> Tuple[List[dict], List[dict]]:
    """Split flight events into ``(spans, points)`` — ``trace_span``
    records vs other trace-stamped events — with wall times mapped onto
    the coordinator's clock (``- offset_s``).  ``trace_id`` filters."""
    spans: List[dict] = []
    points: List[dict] = []
    for ev in events:
        if not isinstance(ev, dict) or not ev.get("trace"):
            continue
        if trace_id is not None and ev["trace"] != trace_id:
            continue
        if ev.get("kind") == SPAN_KIND:
            spans.append({
                "trace": ev["trace"], "span": ev.get("span"),
                "parent": ev.get("parent"),
                "plane": ev.get("plane", "?"),
                "name": ev.get("name", "?"),
                "start": float(ev.get("start", ev.get("ts", 0.0)))
                - offset_s,
                "dur_s": float(ev.get("dur_s") or 0.0),
                "source": source,
                "attrs": {k: v for k, v in ev.items()
                          if k not in _SPAN_FIELDS},
            })
        else:
            points.append({
                "trace": ev["trace"], "span": ev.get("span"),
                "parent": ev.get("parent"),
                "kind": ev.get("kind", "?"),
                "ts": float(ev.get("ts", 0.0)) - offset_s,
                "source": source,
                "attrs": {k: v for k, v in ev.items()
                          if k not in ("ts", "seq", "kind", "trace",
                                       "span", "parent")},
            })
    return spans, points


def _jsonl_points(docs: Sequence[dict], source: str,
                  trace_id: Optional[str], kind_key: str) -> List[dict]:
    out = []
    for d in docs:
        if not d.get("trace"):
            continue
        if trace_id is not None and d["trace"] != trace_id:
            continue
        out.append({
            "trace": d["trace"], "span": d.get("span"),
            "parent": d.get("parent"),
            "kind": str(d.get(kind_key, source)),
            "ts": float(d.get("ts", 0.0)),
            "source": source,
            "attrs": {k: v for k, v in d.items()
                      if k not in ("ts", "trace", "span", "parent",
                                   "traceparent")},
        })
    return out


def collect(flight_paths: Sequence[str] = (),
            obs_dir: Optional[str] = None,
            reqlog_paths: Sequence[str] = (),
            trace_id: Optional[str] = None) -> Dict[str, List[dict]]:
    """Gather ``{"spans": [...], "points": [...]}`` across the planes,
    skew-corrected, optionally filtered to one trace id."""
    spans: List[dict] = []
    points: List[dict] = []
    for path in flight_paths:
        doc = load_flight_dump(path)
        if doc is None:
            continue
        off = float(doc.get("wall_offset_s") or 0.0)
        rank = doc.get("rank")
        s, p = spans_from_events(doc.get("events", []), offset_s=off,
                                 source=f"flight rank {rank}",
                                 trace_id=trace_id)
        spans += s
        points += p
    for path in reqlog_paths:
        points += _jsonl_points(read_jsonl(path), "reqlog", trace_id,
                                "outcome")
    if obs_dir:
        for path in _obs_files(obs_dir, "actions"):
            points += _jsonl_points(read_jsonl(path), "actions",
                                    trace_id, "outcome")
        for path in _obs_files(obs_dir, "obs"):
            docs = [d for d in read_jsonl(path) if "remesh" in d]
            points += _jsonl_points(docs, "remesh", trace_id, "trigger")
    return {"spans": spans, "points": points}


# -- the causal tree ----------------------------------------------------------
def build_tree(data: Dict[str, List[dict]]) -> List[dict]:
    """Join spans + points into trees by span/parent.  A point whose
    span id already has a span record attaches to it as an event;
    otherwise it becomes a (duration-less) node of its own.  Returns
    the roots (parent absent or unknown), children sorted by start."""
    nodes: Dict[str, dict] = {}
    for s in data["spans"]:
        sid = s.get("span")
        if not sid:
            continue
        node = nodes.setdefault(sid, {"events": [], "children": []})
        node.update(s)
    loose: List[dict] = []
    for p in data["points"]:
        sid = p.get("span")
        if sid and sid in nodes and "name" in nodes[sid]:
            nodes[sid]["events"].append(p)
            continue
        if sid:
            node = nodes.setdefault(sid, {"events": [], "children": []})
            if "name" not in node:
                node.update({
                    "trace": p["trace"], "span": sid,
                    "parent": p.get("parent"),
                    "plane": p.get("source", "?"),
                    "name": p["kind"], "start": p["ts"], "dur_s": None,
                    "source": p.get("source"),
                    "attrs": p.get("attrs", {}),
                })
            else:
                node["events"].append(p)
        else:
            loose.append(p)
    roots: List[dict] = []
    for sid, node in nodes.items():
        parent = node.get("parent")
        if parent and parent in nodes:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)

    def _sort(n: dict) -> None:
        n["children"].sort(key=lambda c: c.get("start") or 0.0)
        for c in n["children"]:
            _sort(c)

    roots.sort(key=lambda n: n.get("start") or 0.0)
    for r in roots:
        _sort(r)
    if loose:
        roots.append({"trace": loose[0].get("trace"), "span": None,
                      "parent": None, "plane": "?", "name": "(unbound "
                      "events)", "start": loose[0].get("ts"),
                      "dur_s": None, "events": loose, "children": []})
    return roots


def _fmt_dur(dur: Optional[float]) -> str:
    if dur is None:
        return "·"
    return f"{dur * 1e3:.1f}ms" if dur < 1.0 else f"{dur:.3f}s"


def _render_node(node: dict, lines: List[str], prefix: str,
                 is_last: bool, parent_dur: Optional[float],
                 is_slow: bool = False) -> None:
    branch = "" if prefix == "" and is_last and not lines else \
        ("└─ " if is_last else "├─ ")
    attrs = node.get("attrs") or {}
    extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items())
                     if v is not None and k not in ("source",))
    share = ""
    dur = node.get("dur_s")
    if dur is not None and parent_dur:
        share = f"  [{dur / parent_dur:.0%} of parent]"
    if is_slow:
        share += "  << slow hop"
    src = f"  ({node['source']})" if node.get("source") else ""
    lines.append(f"{prefix}{branch}{node.get('plane', '?')}:"
                 f"{node.get('name', '?')} {_fmt_dur(dur)}"
                 f"{share}{src}" + (f"  {extra}" if extra else ""))
    child_prefix = prefix + ("" if branch == "" else
                             ("   " if is_last else "│  "))
    events = sorted(node.get("events") or [],
                    key=lambda e: e.get("ts") or 0.0)
    for e in events:
        eattrs = " ".join(
            f"{k}={v}" for k, v in sorted((e.get("attrs") or {}).items())
            if v is not None)
        lines.append(f"{child_prefix}• {e['kind']}"
                     f" ({e.get('source', '?')})"
                     + (f"  {eattrs}" if eattrs else ""))
    children = node.get("children") or []
    # the latency attribution: flag the SLOWEST child when it
    # dominates — that hop is where this span's time went
    timed = [c.get("dur_s") or 0.0 for c in children]
    slow_i = timed.index(max(timed)) if timed and max(timed) > 0 \
        else None
    if slow_i is not None and dur and timed[slow_i] < 0.5 * dur:
        slow_i = None  # nothing dominates; no attribution claim
    for i, c in enumerate(children):
        _render_node(c, lines, child_prefix, i == len(children) - 1,
                     dur, is_slow=(i == slow_i))


def render_trace(trace_id: str, data: Dict[str, List[dict]]) -> str:
    """The printable causal tree for one trace id."""
    roots = build_tree(data)
    n_spans = len(data["spans"])
    n_points = len(data["points"])
    planes = sorted({s["plane"] for s in data["spans"]}
                    | {p["source"] for p in data["points"]
                       if p.get("source")})
    lines = [f"trace {trace_id}  ({n_spans} span(s), {n_points} "
             f"event(s), planes: {', '.join(planes) or '-'})"]
    for i, root in enumerate(roots):
        _render_node(root, lines, "", i == len(roots) - 1, None)
    return "\n".join(lines)


# -- the merged timeline ------------------------------------------------------
def _attrs_args(d: dict) -> dict:
    return {k: v for k, v in d.items() if v is not None}


def flight_to_chrome(doc: dict) -> List[dict]:
    """One flight dump → chrome events with ABSOLUTE coordinator-clock
    µs timestamps (``wall_offset_s`` applied): ``trace_span`` records
    become complete (X) spans, everything else instants."""
    off = float(doc.get("wall_offset_s") or 0.0)
    out: List[dict] = []
    for ev in doc.get("events", []):
        if not isinstance(ev, dict):
            continue
        args = _attrs_args({k: v for k, v in ev.items()
                            if k not in ("ts", "seq")})
        if ev.get("kind") == SPAN_KIND:
            start = float(ev.get("start", ev.get("ts", 0.0))) - off
            out.append({
                "ph": "X", "tid": str(ev.get("plane", "trace")),
                "name": f"{ev.get('plane', '?')}:{ev.get('name', '?')}",
                "ts": start * 1e6,
                "dur": max(float(ev.get("dur_s") or 0.0) * 1e6, 1.0),
                "args": args})
        else:
            out.append({
                "ph": "i", "s": "t", "tid": "events",
                "name": str(ev.get("kind", "?")),
                "ts": (float(ev.get("ts", 0.0)) - off) * 1e6,
                "args": args})
    return out


def jsonl_to_chrome(docs: Sequence[dict], kind_key: str) -> List[dict]:
    """Request-log / actions / re-mesh JSONL lines → chrome events.
    ``ok`` request-log lines (which carry ``latency_s``) and re-mesh
    points (``remesh_total_s``) become spans ENDING at their stamp;
    everything else instants."""
    out: List[dict] = []
    for d in docs:
        ts = float(d.get("ts", 0.0))
        args = _attrs_args({k: v for k, v in d.items()
                            if k not in ("ts", "traceparent")})
        dur = d.get("latency_s") if "latency_s" in d \
            else d.get("remesh_total_s")
        if isinstance(dur, (int, float)) and dur > 0:
            out.append({"ph": "X", "tid": "requests",
                        "name": str(d.get(kind_key, "?")),
                        "ts": (ts - float(dur)) * 1e6,
                        "dur": float(dur) * 1e6, "args": args})
        else:
            out.append({"ph": "i", "s": "t", "tid": "events",
                        "name": str(d.get(kind_key, "?")),
                        "ts": ts * 1e6, "args": args})
    return out


def build_timeline(flight_paths: Sequence[str] = (),
                   shard_paths: Sequence[str] = (),
                   reqlog_paths: Sequence[str] = (),
                   obs_dir: Optional[str] = None,
                   out_path: Optional[str] = None) -> Dict[str, Any]:
    """The merged black-box timeline: every plane on one clock.
    Returns (and optionally writes) the chrome trace document."""
    from horovod_tpu.diagnostics.merge import merge_shards
    extra: List[tuple] = []
    for path in flight_paths:
        doc = load_flight_dump(path)
        if doc is None:
            continue
        rank = doc.get("rank")
        extra.append((f"flight rank {rank}", 100 + (rank or 0),
                      flight_to_chrome(doc)))
    for i, path in enumerate(reqlog_paths):
        docs = read_jsonl(path)
        if docs:
            extra.append((f"request log {os.path.basename(path)}",
                          200 + i, jsonl_to_chrome(docs, "outcome")))
    if obs_dir:
        for path in _obs_files(obs_dir, "actions"):
            docs = read_jsonl(path)
            if docs:
                extra.append((f"autopilot {os.path.basename(path)}",
                              300, jsonl_to_chrome(docs, "outcome")))
        for path in _obs_files(obs_dir, "obs"):
            docs = [d for d in read_jsonl(path) if "remesh" in d]
            if docs:
                extra.append((f"re-mesh {os.path.basename(path)}",
                              310, jsonl_to_chrome(docs, "trigger")))
    return merge_shards(shard_paths, out_path, extra_tracks=extra)
