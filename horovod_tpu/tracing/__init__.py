"""Causal fleet tracing: one trace id across every cross-process hop.

The stack records evidence on five disconnected planes — the flight
ring, per-rank timeline shards, the serving request log, the autopilot
decisions JSONL, and re-mesh episodes — so answering "why was this
request slow" or "what caused this re-mesh" used to mean joining JSONL
files by eyeball.  This module is the join key: a dependency-free
W3C-traceparent-style trace context (128-bit trace id, 64-bit span id,
parent span id) that

* travels as a ``traceparent`` HTTP header on router→replica infer
  dispatches (hedged and retried duplicates share the trace id but get
  SIBLING spans), on every KV hop (:mod:`horovod_tpu.runner.http_kv`
  attaches the active context; the relay re-stamps a child per
  forward), and on autopsy peer fetches;
* travels as a ``traceparent`` FIELD inside driver↔worker KV documents
  (drain notices, autopilot ``action/`` requests, the ``drain`` stamp
  of a published world) — the doc outlives the HTTP exchange, so the
  context must ride the payload, not just the connection;
* is stamped into flight-recorder events (automatic: the ring stamps
  the thread's ACTIVE context into every event), serving request-log
  lines, autopilot decision records, and re-mesh episode phases.

The chain finding → decision → ``action/`` doc → driver handling →
drain → re-mesh → first healthy step therefore carries ONE trace id end
to end, and a served request carries one from client submit through
batcher queue, padded forward, and response.  The unified reader
(``python -m horovod_tpu.diagnostics timeline`` / ``... trace <id>``)
merges the planes and prints the causal tree — see
:mod:`horovod_tpu.tracing.reader` and docs/OBSERVABILITY.md
"Causal tracing".

Knobs: ``HVD_TPU_TRACE`` (default on) kills every context source when
0; ``HVD_TPU_TRACE_SAMPLE`` (default 1.0) samples new ROOT traces by
the head of the trace id, so the keep/drop decision is a property of
the id itself and every process agrees on it without coordination.
Metrics: ``hvd_trace_spans_total{plane}`` per created span,
``hvd_trace_dropped_total`` per malformed/refused incoming context.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

from horovod_tpu.common.safe_metrics import safe_inc as _metric

#: the HTTP header / KV-doc field name (W3C trace-context wire format:
#: ``00-<32 hex trace id>-<16 hex span id>-<2 hex flags>``)
TRACEPARENT = "traceparent"

_SPAN_EVENT = "trace_span"  #: flight-recorder event kind for spans

_tls = threading.local()


class TraceContext:
    """One span's identity: ``(trace_id, span_id, parent_id)``.

    ``trace_id`` is 32 lowercase hex chars (128-bit), ``span_id`` and
    ``parent_id`` 16 (64-bit); ``parent_id`` is None for a root span
    and for spans decoded off the wire (the wire format carries only
    trace+span — the receiver's :func:`child` restores parentage)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def fields(self) -> Dict[str, str]:
        """The stamp for log lines / flight events / decision records."""
        out = {"trace": self.trace_id, "span": self.span_id}
        if self.parent_id:
            out["parent"] = self.parent_id
        return out

    def __repr__(self) -> str:  # debugging aid, never parsed
        return (f"TraceContext({self.trace_id[:8]}…, {self.span_id},"
                f" parent={self.parent_id})")

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_id == other.parent_id)


def enabled() -> bool:
    """``HVD_TPU_TRACE`` — default on; 0 makes every context source
    return None, so call sites degrade to the untraced behavior with
    zero per-event cost beyond this check."""
    return os.environ.get("HVD_TPU_TRACE", "") not in ("0", "false",
                                                       "off")


def sample_rate() -> float:
    """``HVD_TPU_TRACE_SAMPLE`` ∈ [0, 1] — fraction of new ROOT traces
    kept (child spans always follow their root's fate)."""
    raw = os.environ.get("HVD_TPU_TRACE_SAMPLE", "")
    if not raw:
        return 1.0
    try:
        return min(max(float(raw), 0.0), 1.0)
    except ValueError:
        return 1.0


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _count(plane: str) -> None:
    _metric("hvd_trace_spans_total",
            "trace spans created, per plane", plane=plane)


def new_trace(plane: str = "generic") -> Optional[TraceContext]:
    """A new root span (None when tracing is off or the trace is
    sampled out).  The sampling decision is derived from the trace id's
    leading 32 bits, so any process re-deriving it from the id alone
    reaches the same verdict."""
    if not enabled():
        return None
    trace_id = _rand_hex(16)
    rate = sample_rate()
    if rate < 1.0 and int(trace_id[:8], 16) / 0xFFFFFFFF >= rate:
        return None
    _count(plane)
    return TraceContext(trace_id, _rand_hex(8), None)


def child(ctx: Optional[TraceContext],
          plane: str = "generic") -> Optional[TraceContext]:
    """A child span of ``ctx`` (None-safe: no parent, no span)."""
    if ctx is None or not enabled():
        return None
    _count(plane)
    return TraceContext(ctx.trace_id, _rand_hex(8), ctx.span_id)


def sibling(ctx: Optional[TraceContext],
            plane: str = "generic") -> Optional[TraceContext]:
    """A SIBLING of ``ctx``: same trace, same parent, fresh span id —
    the identity of a hedged/retried duplicate (one logical request,
    several concurrent attempts)."""
    if ctx is None or not enabled():
        return None
    _count(plane)
    return TraceContext(ctx.trace_id, _rand_hex(8), ctx.parent_id)


def encode(ctx: Optional[TraceContext]) -> Optional[str]:
    return ctx.traceparent if ctx is not None else None


def _is_hex(s: str, n: int) -> bool:
    if len(s) != n:
        return False
    try:
        int(s, 16)
        return True
    except ValueError:
        return False


def decode(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``traceparent`` value; a malformed header is IGNORED
    (None + ``hvd_trace_dropped_total``) — never an error on the
    serving/control path.  An absent header (None/empty) is simply
    untraced, not a drop."""
    if not header or not enabled():
        return None
    parts = str(header).strip().split("-")
    if (len(parts) == 4 and parts[0] == "00"
            and _is_hex(parts[1], 32) and _is_hex(parts[2], 16)
            and int(parts[1], 16) != 0 and int(parts[2], 16) != 0):
        return TraceContext(parts[1].lower(), parts[2].lower(), None)
    _metric("hvd_trace_dropped_total",
            "malformed/refused incoming trace contexts (the event "
            "proceeds untraced)")
    return None


def from_doc(doc: Any) -> Optional[TraceContext]:
    """The context a KV document carries (``doc["traceparent"]``)."""
    if isinstance(doc, dict):
        return decode(doc.get(TRACEPARENT))
    return None


# -- thread-local active context ----------------------------------------------
def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> None:
    _tls.ctx = ctx


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]) -> Iterator[
        Optional[TraceContext]]:
    """Make ``ctx`` the thread's active context for the block: flight
    events recorded inside are stamped with it, and outbound KV calls
    attach it as the ``traceparent`` header.  None deactivates (an
    untraced block inside a traced one stays untraced)."""
    prev = current()
    set_current(ctx)
    try:
        yield ctx
    finally:
        set_current(prev)


def fields(ctx: Optional[TraceContext]) -> Dict[str, str]:
    return ctx.fields() if ctx is not None else {}


def record_span(plane: str, name: str, ctx: Optional[TraceContext],
                start: Optional[float] = None,
                dur_s: Optional[float] = None, **attrs: Any) -> None:
    """Record one completed span into the flight ring (kind
    ``trace_span``): the durable form every reader joins on.  ``start``
    is wall-clock seconds (default now − dur), ``dur_s`` the span's
    measured duration.  No-op without a context; never raises."""
    if ctx is None:
        return
    try:
        if dur_s is None:
            dur_s = 0.0
        if start is None:
            start = time.time() - dur_s
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(_SPAN_EVENT, plane=plane, name=name,
                     start=round(float(start), 6),
                     dur_s=round(float(dur_s), 6), **ctx.fields(),
                     **{k: v for k, v in attrs.items() if v is not None})
    except Exception:
        pass  # tracing must never take down the traced path
