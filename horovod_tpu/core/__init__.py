"""Native C++ coordination core (libhvdcore).

TPU-native re-implementation of the reference's C++ core
(``horovod/common/operations.cc`` background thread + controller + fusion +
response cache) with a TCP transport replacing Gloo. Built as a shared
library loaded via ctypes — see :mod:`horovod_tpu.core.bindings`.
"""

from __future__ import annotations

import os


def _lib_path() -> str:
    # HVD_TPU_CORE_LIB overrides (e.g. the `make tsan` ThreadSanitizer build)
    override = os.environ.get("HVD_TPU_CORE_LIB")
    if override:
        return override
    return os.path.join(os.path.dirname(__file__), "libhvdcore.so")


def core_available() -> bool:
    return os.path.exists(_lib_path())
