"""Gate between the Python surface and the native core: raises a
build-instruction error for multi-process runs without ``libhvdcore.so``,
hands back a :class:`CoreBackend` otherwise.

Reference analog: ``horovod/common/basics.py:29-149`` loading the C library
and exposing ``horovod_init``/enqueue functions (the full ctypes surface
lives in ``core_backend.py``).
"""

from __future__ import annotations

from horovod_tpu.core import core_available, _lib_path


def core_backend_or_raise(state):
    if not core_available():
        raise RuntimeError(
            f"horovod_tpu was launched with size={state.size} > 1 but the "
            f"native core library is not built ({_lib_path()} missing). "
            "Build it with `python setup.py build_ext` or run single-process.")
    from horovod_tpu.core.core_backend import CoreBackend
    return CoreBackend(state)


def core_config_dump() -> dict:
    """Parsed env-knob values as seen by the C++ core (key=value map) —
    lets tests assert the env round-trips into the engine without booting
    a full multi-process world."""
    from horovod_tpu.core.core_backend import _load_lib
    text = _load_lib().hvd_cfg_dump().decode()
    out = {}
    for line in text.strip().splitlines():
        k, _, v = line.partition("=")
        out[k] = v
    return out
