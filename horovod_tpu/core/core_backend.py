"""ctypes Backend over libhvdcore — the multi-process eager path.

Reference analog: ``horovod/common/basics.py`` (ctypes init/identity) +
``horovod/torch/mpi_ops_v2.cc`` (enqueue + handle manager). Arrays are
moved to host (numpy), enqueued into the C++ core (which negotiates,
fuses and runs TCP ring collectives), and returned in the caller's array
flavor.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Optional, Sequence

import numpy as np

from horovod_tpu.core import _lib_path
from horovod_tpu.ops.backend import Backend, HvdHandle
from horovod_tpu.ops.reduce_op import ReduceOp

_LIB = None
_LIB_LOCK = threading.Lock()

# DataType codes must match cpp/types.h
_DTYPE_CODES = {
    "uint8": 0, "int8": 1, "int32": 4, "int64": 5,
    "float16": 6, "float32": 7, "float64": 8, "bool": 9, "bfloat16": 10,
}


def _load_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        lib = ctypes.CDLL(_lib_path())
        lib.hvd_init.restype = ctypes.c_int
        lib.hvd_last_error.restype = ctypes.c_char_p
        lib.hvd_cfg_dump.restype = ctypes.c_char_p
        lib.hvd_rank.restype = ctypes.c_int
        lib.hvd_size.restype = ctypes.c_int
        lib.hvd_enqueue_allreduce.restype = ctypes.c_int
        lib.hvd_enqueue_allreduce.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_int]
        lib.hvd_enqueue_grouped_allreduce.restype = ctypes.c_int
        lib.hvd_enqueue_grouped_allreduce.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        lib.hvd_enqueue_allgather.restype = ctypes.c_int
        lib.hvd_enqueue_allgather.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.hvd_enqueue_broadcast.restype = ctypes.c_int
        lib.hvd_enqueue_broadcast.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int]
        lib.hvd_enqueue_alltoall.restype = ctypes.c_int
        lib.hvd_enqueue_alltoall.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.hvd_enqueue_join.restype = ctypes.c_int
        lib.hvd_barrier.restype = ctypes.c_int
        lib.hvd_poll.restype = ctypes.c_int
        lib.hvd_wait.restype = ctypes.c_int
        lib.hvd_wait.argtypes = [ctypes.c_int, ctypes.c_double]
        lib.hvd_result_ndim.restype = ctypes.c_int
        lib.hvd_result_shape.restype = ctypes.c_int
        lib.hvd_result_shape.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.hvd_recv_splits.restype = ctypes.c_int
        lib.hvd_recv_splits.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.hvd_copy_result.restype = ctypes.c_int
        lib.hvd_copy_result.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                        ctypes.c_int64]
        lib.hvd_add_process_set.restype = ctypes.c_int
        lib.hvd_last_join_rank.restype = ctypes.c_int
        lib.hvd_counters_json.restype = ctypes.c_char_p
        # tolerate an older/sanitizer build of the lib (HVD_TPU_CORE_LIB
        # override) that predates the straggler API
        if hasattr(lib, "hvd_stragglers_json"):
            lib.hvd_stragglers_json.restype = ctypes.c_char_p
        # ... or the diagnostics APIs (engine state + span marks)
        if hasattr(lib, "hvd_engine_state_json"):
            lib.hvd_engine_state_json.restype = ctypes.c_char_p
        if hasattr(lib, "hvd_timeline_enabled"):
            lib.hvd_timeline_enabled.restype = ctypes.c_int
        if hasattr(lib, "hvd_timeline_mark"):
            lib.hvd_timeline_mark.restype = None
            lib.hvd_timeline_mark.argtypes = [ctypes.c_char_p,
                                              ctypes.c_char_p]
        lib.hvd_start_timeline.restype = ctypes.c_int
        lib.hvd_start_timeline.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.hvd_stop_timeline.restype = ctypes.c_int
        _LIB = lib
        return lib


def _np_dtype_code(dtype) -> int:
    name = np.dtype(dtype).name
    if name == "bool":
        name = "bool"
    code = _DTYPE_CODES.get(name)
    if code is None:
        # jax bfloat16 comes through ml_dtypes
        if "bfloat16" in str(dtype):
            return 10
        raise TypeError(f"unsupported dtype for core collectives: {dtype}")
    return code


def _to_host(value):
    """Return (contiguous numpy array, reconstruct_fn).

    np.ascontiguousarray promotes 0-d to (1,); reshape back so scalar
    tensors keep their shape through the collective (a scalar optimizer
    slot like SGD/iteration must broadcast back as a scalar)."""
    base = np.asarray(value)
    try:
        import jax
        if isinstance(value, jax.Array):
            import jax.numpy as jnp
            return (np.ascontiguousarray(base).reshape(base.shape),
                    lambda a: jnp.asarray(a))
    except ImportError:
        pass
    return np.ascontiguousarray(base).reshape(base.shape), lambda a: a


def _shape_arg(shape):
    arr = (ctypes.c_int64 * max(len(shape), 1))(*shape)
    return arr, len(shape)


# Buffers referenced by in-flight C++ entries. Keyed by C handle id and
# released on completion; a handle abandoned without wait() leaks its
# buffers here rather than letting the background thread write freed memory
# (the reference keeps tensors alive in the tensor table the same way).
_INFLIGHT_BUFFERS = {}
_INFLIGHT_LOCK = threading.Lock()


def _pin_buffers(ch: int, bufs) -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT_BUFFERS[ch] = bufs


def _unpin_buffers(ch: int) -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT_BUFFERS.pop(ch, None)


class CoreHandle(HvdHandle):
    """Handle backed by the C++ handle manager (polls the core instead of a
    Python event)."""

    def __init__(self, lib, ch: int, finisher):
        super().__init__()
        self._lib = lib
        self._ch = ch
        self._finisher = finisher
        self._finished = False
        self._flock = threading.Lock()

    def poll(self) -> bool:
        if self._finished:
            return True
        return bool(self._lib.hvd_poll(self._ch))

    def wait(self, timeout: Optional[float] = None):
        with self._flock:
            if self._finished:
                return super().wait(0)
            t = 1e9 if timeout is None else float(timeout)
            rc = self._lib.hvd_wait(self._ch, t)
            if rc == -2:
                # timed out: handle and buffers stay valid for a retry
                raise TimeoutError("collective did not complete in time")
            if rc != 0:
                err = self._lib.hvd_last_error().decode()
                # HorovodInternalError (a RuntimeError) so elastic.run's
                # restore()-and-retry path actually triggers on peer failure
                from horovod_tpu.elastic import HorovodInternalError
                self._set_error(
                    HorovodInternalError(f"collective failed: {err}"))
            else:
                try:
                    self._set_result(self._finisher())
                except BaseException as e:
                    self._set_error(e)
            self._lib.hvd_free_handle(self._ch)
            _unpin_buffers(self._ch)
            self._finished = True
        return super().wait(0)


class CoreBackend(Backend):
    """Backend over the native core for one coordination domain."""

    def __init__(self, state=None, domain: int = 0, rank: int = None,
                 size: int = None, lib=None, owns_core: bool = None):
        self._lib = lib or _load_lib()
        if domain == 0:
            # chaos harness: raw-core workers (no hvd.init) still honor
            # HVD_TPU_FAULT_PLAN — the transport env spec must be
            # compiled before the C++ core reads it at Transport::Init
            from horovod_tpu import chaos
            chaos.install()
            rc = self._lib.hvd_init()
            if rc != 0:
                raise RuntimeError("hvdcore init failed: " +
                                   self._lib.hvd_last_error().decode())
            rank = self._lib.hvd_rank()
            size = self._lib.hvd_size()
            self._owns_core = True if owns_core is None else owns_core
            self._group_counter = 0
            self._group_lock = threading.Lock()
            # hvd.init(ranks=...) restriction: the "global" set is a subset
            # of the launched world (reference: init_multi_comm,
            # operations.cc:881-965). The core still spans the full world;
            # the restricted global set is a process-set domain.
            world_ranks = getattr(state, "world_ranks", None) if state else \
                None
            if world_ranks is not None and list(world_ranks) != \
                    list(range(size)):
                super().__init__(rank, size)  # temp for make_subset
                self._domain = 0
                sub = self.make_subset(world_ranks)
                self._domain = sub._domain
                self._ranks = sub._ranks
                rank = sub.rank
                size = sub.size
                super().__init__(rank, size)
                self._group_counter = 0
                self._group_lock = threading.Lock()
                return
        else:
            self._owns_core = False
        super().__init__(rank, size)
        self._domain = domain
        self._group_counter = 0
        self._group_lock = threading.Lock()

    # -- collectives ---------------------------------------------------------
    def allreduce_async(self, name, value, op, prescale=1.0, postscale=1.0,
                        group_id=-1, group_size=0):
        arr, back = _to_host(value)
        out = np.empty_like(arr)
        sh, nd = _shape_arg(arr.shape)
        if group_id >= 0:
            ch = self._lib.hvd_enqueue_grouped_allreduce(
                name.encode(), arr.ctypes.data_as(ctypes.c_void_p),
                out.ctypes.data_as(ctypes.c_void_p),
                _np_dtype_code(arr.dtype), nd, sh, int(op),
                float(prescale), float(postscale), self._domain,
                int(group_id), int(group_size))
        else:
            ch = self._lib.hvd_enqueue_allreduce(
                name.encode(), arr.ctypes.data_as(ctypes.c_void_p),
                out.ctypes.data_as(ctypes.c_void_p),
                _np_dtype_code(arr.dtype), nd, sh, int(op),
                float(prescale), float(postscale), self._domain)
        _pin_buffers(ch, (arr, out))
        return CoreHandle(self._lib, ch, lambda: back(out))

    def grouped_allreduce_async(self, names, values, op,
                                prescale=1.0, postscale=1.0):
        # a registered group (reference: GroupTable): the coordinator holds
        # the whole group back until every member is ready (group-complete
        # negotiation; fusion still bounds unit sizes). The id counter is
        # per-backend (per coordination domain) so sub-set usage on one
        # rank can't skew another domain's sequence; as with names, all
        # members of a domain must make grouped calls in the same order.
        with self._group_lock:
            self._group_counter += 1
            gid = self._group_counter
        handles = [self.allreduce_async(n, v, op, prescale, postscale,
                                        group_id=gid,
                                        group_size=len(values))
                   for n, v in zip(names, values)]
        agg = HvdHandle()

        def waiter():
            try:
                agg._set_result([h.wait() for h in handles])
            except BaseException as e:
                agg._set_error(e)

        threading.Thread(target=waiter, daemon=True).start()
        return agg

    def allgather_async(self, name, value):
        arr, back = _to_host(value)
        sh, nd = _shape_arg(arr.shape)
        ch = self._lib.hvd_enqueue_allgather(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p),
            _np_dtype_code(arr.dtype), nd, sh, self._domain)

        def finish():
            ndim = self._lib.hvd_result_ndim(ch)
            shape = (ctypes.c_int64 * max(ndim, 1))()
            self._lib.hvd_result_shape(ch, shape, ndim)
            out_shape = tuple(shape[i] for i in range(ndim))
            out = np.empty(out_shape, dtype=arr.dtype)
            self._lib.hvd_copy_result(
                ch, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
            return back(out)

        _pin_buffers(ch, (arr,))
        return CoreHandle(self._lib, ch, finish)

    def broadcast_async(self, name, value, root_rank):
        arr, back = _to_host(value)
        out = np.array(arr, copy=True)
        sh, nd = _shape_arg(arr.shape)
        # root_rank is the GLOBAL rank at the API boundary, matching the
        # reference (operations.cc:1560-1592 converts global → set rank
        # internally); the C++ core wants the global rank directly.
        ranks = getattr(self, "_ranks", None)
        globl = int(root_rank)
        if ranks is not None and globl not in ranks:
            raise ValueError(
                f"broadcast root_rank={root_rank} is not a member of "
                f"process set {ranks}")
        ch = self._lib.hvd_enqueue_broadcast(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p),
            out.ctypes.data_as(ctypes.c_void_p), globl,
            _np_dtype_code(arr.dtype), nd, sh, self._domain)
        _pin_buffers(ch, (arr, out))
        return CoreHandle(self._lib, ch, lambda: back(out))

    def alltoall_async(self, name, value, splits=None):
        arr, back = _to_host(value)
        if splits is None:
            if arr.shape[0] % self.size != 0:
                raise ValueError(
                    "alltoall without splits requires dim 0 divisible by "
                    f"size ({self.size})")
            splits = [arr.shape[0] // self.size] * self.size
        splits = list(int(s) for s in splits)
        if len(splits) != self.size:
            raise ValueError("alltoall splits must have one entry per rank")
        sp = (ctypes.c_int64 * len(splits))(*splits)
        sh, nd = _shape_arg(arr.shape)
        ch = self._lib.hvd_enqueue_alltoall(
            name.encode(), arr.ctypes.data_as(ctypes.c_void_p), sp,
            len(splits), _np_dtype_code(arr.dtype), nd, sh, self._domain)

        def finish():
            ndim = self._lib.hvd_result_ndim(ch)
            shape = (ctypes.c_int64 * max(ndim, 1))()
            self._lib.hvd_result_shape(ch, shape, ndim)
            out_shape = tuple(shape[i] for i in range(ndim))
            out = np.empty(out_shape, dtype=arr.dtype)
            self._lib.hvd_copy_result(
                ch, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
            rs = (ctypes.c_int64 * self.size)()
            nrs = self._lib.hvd_recv_splits(ch, rs, self.size)
            recv_splits = np.asarray([rs[i] for i in range(nrs)],
                                     dtype=np.int32)
            return back(out), recv_splits

        _pin_buffers(ch, (arr,))
        return CoreHandle(self._lib, ch, finish)

    def barrier(self):
        rc = self._lib.hvd_barrier(self._domain)
        if rc != 0:
            raise RuntimeError("barrier failed: " +
                               self._lib.hvd_last_error().decode())

    def join(self, device: int = -1) -> int:
        ch = self._lib.hvd_enqueue_join(self._domain)
        CoreHandle(self._lib, ch, lambda: None).wait()
        return self._lib.hvd_last_join_rank(self._domain)

    # -- observability -------------------------------------------------------
    def counters(self) -> dict:
        """Engine control-plane counters (cpp hvd_counters_json): cycles,
        cache hits/misses/evictions, responses executed, fusion stats,
        bytes moved."""
        import json
        return json.loads(self._lib.hvd_counters_json().decode())

    def stragglers(self) -> dict:
        """Coordinator-side rank-attributed negotiation-wait report
        (cpp hvd_stragglers_json): per rank, the total seconds peers
        spent waiting on it being the last to announce a tensor, and the
        count of tensors it held up. Empty ``ranks`` away from the
        coordinator (only rank 0 sees every announcement)."""
        import json
        if not hasattr(self._lib, "hvd_stragglers_json"):
            return {}
        return json.loads(self._lib.hvd_stragglers_json().decode())

    def engine_state(self) -> dict:
        """Pending-tensor autopsy snapshot (cpp hvd_engine_state_json):
        per coordination domain, the tensors waiting for announcements
        with ready/missing ranks, queue depth and join state.  Published
        by the engine loop at <=2 Hz; empty away from the coordinator
        (only rank 0 tracks readiness)."""
        import json
        if not hasattr(self._lib, "hvd_engine_state_json"):
            return {}
        return json.loads(self._lib.hvd_engine_state_json().decode())

    def core_timeline_enabled(self) -> bool:
        if not hasattr(self._lib, "hvd_timeline_enabled"):
            return False
        return bool(self._lib.hvd_timeline_enabled())

    def timeline_mark(self, name: str, span: str) -> None:
        """Stamp an eager-enqueue marker with its span id into the
        engine's timeline (diagnostics cross-rank trace)."""
        if hasattr(self._lib, "hvd_timeline_mark"):
            self._lib.hvd_timeline_mark(name.encode(), span.encode())

    def start_core_timeline(self, file_path: str,
                            mark_cycles: bool = False) -> bool:
        """Dynamic start of the engine's chrome-tracing timeline
        (coordinator-only file; reference operations.cc:1011-1041)."""
        rc = self._lib.hvd_start_timeline(file_path.encode(),
                                          1 if mark_cycles else 0)
        if rc != 0:
            raise RuntimeError("start_timeline failed: " +
                               self._lib.hvd_last_error().decode())
        return True

    def stop_core_timeline(self) -> bool:
        rc = self._lib.hvd_stop_timeline()
        if rc != 0:
            raise RuntimeError("stop_timeline failed: " +
                               self._lib.hvd_last_error().decode())
        return True

    # -- lifecycle -----------------------------------------------------------
    def make_subset(self, ranks: Sequence[int]):
        ranks = sorted(set(int(r) for r in ranks))
        arr = (ctypes.c_int * len(ranks))(*ranks)
        domain = self._lib.hvd_add_process_set(arr, len(ranks))
        my_global = self._lib.hvd_rank()
        sub_rank = ranks.index(my_global) if my_global in ranks else -1
        be = CoreBackend(domain=domain, rank=sub_rank, size=len(ranks),
                         lib=self._lib)
        be._ranks = ranks
        return be

    def shutdown(self, force: bool = False):
        if self._owns_core:
            if force and hasattr(self._lib, "hvd_shutdown_force"):
                # skip the 10s consensus grace: the caller knows a peer
                # is dead (elastic in-place shrink)
                self._lib.hvd_shutdown_force()
            else:
                self._lib.hvd_shutdown()
        elif self._domain != 0:
            self._lib.hvd_remove_process_set(self._domain)
