"""Elastic training on Spark clusters.

Reference: ``horovod.spark.run_elastic`` (``spark/runner.py:309-430``) —
there, Spark tasks host task services the driver execs workers through,
and the elastic driver treats the set of live task services as its host
universe. Same architecture here over the shared agent transport
(:mod:`horovod_tpu.runner.elastic.agent`): every Spark task runs the host
agent loop; executor loss → heartbeat expiry → the driver shrinks;
Spark's task retry respawns the agent → the driver grows back. The data
plane is the ordinary TCP core rendezvous the workers set up among
themselves.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from horovod_tpu.runner.elastic.agent import run_agent_elastic


def run_elastic(fn: Callable, args: tuple = (),
                kwargs: Optional[dict] = None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                env: Optional[dict] = None,
                reset_limit: Optional[int] = None,
                verbose: int = 0) -> List[Any]:
    """Elastic ``horovod.spark.run_elastic`` contract
    (``spark/runner.py:309-430``): run ``fn`` — which uses the
    ``hvd.elastic`` API internally, reference-style — on Spark tasks that
    may come and go, returning per-rank results of the generation that
    completed."""
    from horovod_tpu.spark import _require_pyspark

    _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or int(sc.defaultParallelism)

    def start_agents(ctx) -> Callable[[], None]:
        kv_addr, kv_port = ctx["kv_addr"], ctx["kv_port"]
        secret_hex = ctx["secret_hex"]
        world_secret_hex = ctx["world_secret_hex"]
        n = ctx["max_np"]

        def spark_job():
            def task(it):
                from horovod_tpu.runner.elastic.agent import (
                    agent_loop, resolve_kv_addr)
                for ordinal in it:
                    agent_loop(int(ordinal), resolve_kv_addr(kv_addr),
                               kv_port, secret_hex, world_secret_hex)
                return iter([(0, b"")])

            sc.parallelize(range(n), n).mapPartitions(task).collect()

        job = threading.Thread(target=spark_job, daemon=True)
        job.start()
        return lambda: job.join(timeout=30)

    return run_agent_elastic(
        start_agents, fn, args, kwargs, num_proc=num_proc, min_np=min_np,
        max_np=max_np, env=env, reset_limit=reset_limit, verbose=verbose)
