"""Elastic training on Spark clusters.

Reference: ``horovod.spark.run_elastic`` (``spark/runner.py:309-430``) —
there, Spark tasks host task services the driver execs workers through,
and the elastic driver treats the set of live task services as its host
universe. Same architecture here, TPU-launcher-native: every Spark task
runs a small HOST AGENT loop that registers itself in a driver-side KV
(heartbeat), executes HMAC-signed worker commands the ElasticDriver
routes to it, and reports exit codes. Executor loss → heartbeat expiry →
the driver shrinks; Spark's task retry respawns the agent → the driver
grows back. The data plane is the ordinary TCP core rendezvous the
workers set up among themselves.

Trust model: command docs are integrity-protected (HMAC over a secret
shipped through Spark's own task-serialization channel, never the KV),
and secrets — including the elastic world-doc key — stay off the wire;
the KV itself, like the reference's rendezvous server and Spark's own
block transfer service, assumes the cluster-private network. Do not
expose the driver KV port outside that network.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import subprocess
import sys
import threading
import time
import uuid as uuidlib
from typing import Any, Callable, Dict, List, Optional

HEARTBEAT_S = 1.0
STALE_S = 10.0


def _sign(secret: bytes, body: bytes) -> str:
    return hmac.new(secret, body, hashlib.sha256).hexdigest()


# -- agent side (runs inside a Spark task) ----------------------------------

def _agent_loop(ordinal: int, kv_addr: str, kv_port: int,
                secret_hex: str, world_secret_hex: str = "") -> None:
    """Register as a host agent and execute signed worker commands until
    the driver posts shutdown (reference analog: the task service loop,
    ``spark/driver/`` + ``runner/common/service/task_service.py``).

    The world-doc secret arrives through Spark's own task-serialization
    channel (this function's arguments), NOT over the KV — the agent
    injects it into each worker's environment locally."""
    import collections
    import socket
    from horovod_tpu.runner.http_kv import kv_get, kv_put

    secret = bytes.fromhex(secret_hex)
    host = socket.gethostname()
    agent_id = f"{host}@{ordinal}"  # '@' is URL-path-safe; '#' would be
    # stripped as a URI fragment by the HTTP KV client
    seen = collections.OrderedDict()  # bounded processed-uuid memory
    proc: Optional[subprocess.Popen] = None
    cur_uuid: Optional[str] = None

    def beat() -> None:
        kv_put(kv_addr, kv_port, "agents", agent_id, json.dumps(
            {"host": host, "ts": time.time()}).encode())

    beat()
    last_beat = time.time()
    while True:
        now = time.time()
        if now - last_beat >= HEARTBEAT_S:
            beat()
            last_beat = now
        if kv_get(kv_addr, kv_port, "ctl", "shutdown") is not None:
            if proc is not None and proc.poll() is None:
                proc.terminate()
            return
        if proc is not None:
            if kv_get(kv_addr, kv_port, "kill", cur_uuid) is not None \
                    and proc.poll() is None:
                proc.terminate()
            rc = proc.poll()
            if rc is not None:
                kv_put(kv_addr, kv_port, "rc", cur_uuid,
                       str(rc).encode())
                proc, cur_uuid = None, None
        else:
            doc = kv_get(kv_addr, kv_port, "cmd", agent_id)
            if doc:
                body, _, sig = doc.rpartition(b"|")
                if sig and hmac.compare_digest(sig.decode(),
                                               _sign(secret, body)):
                    spec = json.loads(body)
                    if spec["uuid"] not in seen:
                        seen[spec["uuid"]] = True
                        while len(seen) > 64:
                            seen.popitem(last=False)
                        cur_uuid = spec["uuid"]
                        wenv = {**os.environ, **spec["env"]}
                        if world_secret_hex:
                            wenv["HVD_ELASTIC_SECRET"] = world_secret_hex
                        proc = subprocess.Popen(spec["cmd"], env=wenv)
        time.sleep(0.25)


# -- driver side ------------------------------------------------------------

class SparkAgentDiscovery:
    """Host discovery over the agent registry: one slot per agent whose
    heartbeat is fresh (reference analog: the driver's view of registered
    task services)."""

    def __init__(self, kv) -> None:
        self._kv = kv

    def agents_on(self, host: str) -> List[str]:
        out = []
        for agent_id, blob in sorted(self._kv.scope("agents").items()):
            meta = json.loads(blob)
            if meta["host"] == host and \
                    time.time() - meta["ts"] < STALE_S:
                out.append(agent_id)
        return out

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        slots: Dict[str, int] = {}
        for agent_id, blob in self._kv.scope("agents").items():
            meta = json.loads(blob)
            if time.time() - meta["ts"] < STALE_S:
                slots[meta["host"]] = slots.get(meta["host"], 0) + 1
        return slots


_ENV_SHIP_PREFIXES = ("HOROVOD_", "HVD_", "PATH", "PYTHONPATH")


def _make_agent_exec(kv, discovery: SparkAgentDiscovery, secret: bytes,
                     user_env_keys=()):
    """remote_exec for ElasticDriver: route (command, env) to the agent
    occupying this slot and wait for its exit code.

    Only launcher-owned env keys (and the caller's explicit ``env``
    overrides) travel in the command doc — the agent merges them over ITS
    executor environment, so driver-side credentials never cross the
    network (the ssh launcher filters exports the same way,
    ``exec_run.py slot_command``)."""

    def _exec(slot, command: List[str], wenv: Dict[str, str],
              events) -> int:
        agents = discovery.agents_on(slot.hostname)
        if len(agents) <= slot.local_rank:
            # an agent's heartbeat went stale between assignment and
            # launch; failing the slot restarts the generation cleanly
            # rather than doubling two slots onto one agent
            return 1
        agent_id = agents[slot.local_rank]
        uid = uuidlib.uuid4().hex
        ship = {k: v for k, v in wenv.items()
                if isinstance(v, str) and
                (k.startswith(_ENV_SHIP_PREFIXES) or k in user_env_keys)}
        body = json.dumps(
            {"uuid": uid, "cmd": list(command), "env": ship}).encode()
        kv.put("cmd", agent_id, body + b"|" + _sign(secret, body).encode())
        killed = False
        kill_deadline = None
        while True:
            rc = kv.get("rc", uid)
            if rc is not None:
                # retire the doc so the KV doesn't accumulate a full env
                # copy per launch over a long elastic job
                kv.put("cmd", agent_id, b"")
                return int(rc)
            if not killed and any(e.is_set() for e in events):
                kv.put("kill", uid, b"1")
                killed = True
                kill_deadline = time.time() + 3 * STALE_S
            # a dead agent never posts rc: give up once its heartbeat is
            # stale (executor loss) or a kill went unacknowledged
            if agent_id not in discovery.agents_on(slot.hostname) or \
                    (kill_deadline and time.time() > kill_deadline):
                return 1
            time.sleep(0.1)

    return _exec


def run_elastic(fn: Callable, args: tuple = (),
                kwargs: Optional[dict] = None,
                num_proc: Optional[int] = None,
                min_np: Optional[int] = None,
                max_np: Optional[int] = None,
                env: Optional[dict] = None,
                reset_limit: Optional[int] = None,
                verbose: int = 0) -> List[Any]:
    """Elastic ``horovod.spark.run_elastic`` contract
    (``spark/runner.py:309-430``): run ``fn`` — which uses the
    ``hvd.elastic`` API internally, reference-style — on Spark tasks that
    may come and go, returning per-rank results of the generation that
    completed."""
    import cloudpickle
    from horovod_tpu.spark import _require_pyspark
    from horovod_tpu.runner.http_kv import KVStoreServer
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    _require_pyspark()
    from pyspark.sql import SparkSession

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or int(sc.defaultParallelism)
    min_np = min_np or num_proc
    max_np = max_np or num_proc

    kv = KVStoreServer()
    kv.start()
    import secrets as _secrets
    import socket as _socket
    secret = _secrets.token_bytes(16)
    world_secret = _secrets.token_bytes(16)
    kv.put("payload", "fn", cloudpickle.dumps((fn, args, kwargs)))
    # advertise the hostname, not getfqdn(): agents on other hosts resolve
    # it via cluster DNS (the reference's task-address model) and same-host
    # agents shortcut to loopback; getfqdn() can be 'localhost', which
    # resolves to ::1 while the KV server is IPv4-only
    kv_addr = _socket.gethostname()
    kv_port, secret_hex = kv.port, secret.hex()
    world_secret_hex = world_secret.hex()

    def spark_job():
        def task(it):
            import socket
            from horovod_tpu.spark.elastic import _agent_loop
            for ordinal in it:
                addr = kv_addr
                # same-box fast path (and the fake-cluster tests)
                if socket.gethostname() == addr.split(".")[0]:
                    addr = "127.0.0.1"
                _agent_loop(int(ordinal), addr, kv_port, secret_hex,
                            world_secret_hex)
            return iter([(0, b"")])

        sc.parallelize(range(max_np), max_np).mapPartitions(task).collect()

    job = threading.Thread(target=spark_job, daemon=True)
    job.start()

    discovery = SparkAgentDiscovery(kv)
    worker_env = dict(os.environ)
    worker_env.update(env or {})
    worker_env["HVD_SPARK_KV"] = f"{kv_addr}:{kv_port}"
    driver = ElasticDriver(
        discovery,
        [sys.executable, "-u", "-m", "horovod_tpu.spark.elastic_worker"],
        min_np=min_np, max_np=max_np, env=worker_env,
        reset_limit=reset_limit, verbose=bool(verbose),
        target_np=num_proc, world_secret=world_secret,
        remote_exec=_make_agent_exec(kv, discovery, secret,
                                     user_env_keys=tuple(env or ())))
    try:
        rc = driver.run()
        if rc != 0:
            raise RuntimeError(
                f"elastic Spark job failed (driver rc={rc})")
        # only the generation that completed counts: a rank that finished
        # inside an ABORTED world may have published a result too
        final_np = driver.final_np or 0
        results: Dict[int, Any] = {}
        for key, blob in kv.scope("result").items():
            if int(key) < final_np:
                results[int(key)] = cloudpickle.loads(blob)
        if sorted(results) != list(range(final_np)):
            raise RuntimeError(
                f"elastic Spark job succeeded but results are missing: "
                f"have ranks {sorted(results)}, expected 0..{final_np - 1}")
        return [results[r] for r in range(final_np)]
    finally:
        kv.put("ctl", "shutdown", b"1")
        job.join(timeout=30)
        kv.stop()
