"""Spark cluster integration.

Reference: ``horovod/spark/runner.py`` — ``horovod.spark.run(fn, ...)``
spawns task services in Spark executors, collects host info on the driver,
launches the distributed job over them, and returns per-rank results
(:197-306). This module provides the same contract on top of the TPU
launcher: each Spark task hosts one worker process (one TPU host).

Gated on pyspark availability (not bundled in this image); the Store
abstraction (reference: ``spark/common/store.py:36-530``) is usable without
Spark for checkpoint/output management.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional

from horovod_tpu.spark.store import FilesystemStore, LocalStore, Store  # noqa: F401
from horovod_tpu.spark.estimator import (  # noqa: F401
    HorovodEstimator, HorovodModel)
from horovod_tpu.spark.keras_estimator import (  # noqa: F401
    KerasEstimator, KerasModel)
from horovod_tpu.spark.torch_estimator import (  # noqa: F401
    TorchEstimator, TorchModel)


def run_elastic(*args, **kwargs):
    """Elastic Spark launch (reference: ``horovod.spark.run_elastic``,
    ``spark/runner.py:309``); see :mod:`horovod_tpu.spark.elastic`."""
    from horovod_tpu.spark.elastic import run_elastic as _impl
    return _impl(*args, **kwargs)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment. Install pyspark to use Spark-cluster "
            "launching; the rest of horovod_tpu works without it.") from e


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, env: Optional[dict] = None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks with horovod_tpu initialized
    (reference: ``horovod.spark.run``, ``spark/runner.py:197-306``).

    Strategy: a barrier-mode Spark job where every task reports its host to
    the driver via the accumulated host list, then rank 0's host runs the
    coordinator and each task execs the worker fn — mirroring the
    reference's task-service handshake with Spark's own scheduling.
    """
    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession
    import cloudpickle

    kwargs = kwargs or {}
    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or int(sc.defaultParallelism)
    payload = cloudpickle.dumps((fn, args, kwargs))
    extra_env = dict(env or {})

    def task(idx_it):
        import socket
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        infos = ctx.getTaskInfos()
        coord = infos[0].address.split(":")[0]
        # rank 0 binds a free port on ITS host and shares it with everyone
        my_port = ""
        if rank == 0:
            s = socket.socket()
            s.bind(("0.0.0.0", 0))
            my_port = str(s.getsockname()[1])
            s.close()
        coord_port = int(ctx.allGather(my_port)[0])
        os.environ.update(extra_env)
        os.environ.update({
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(len(infos)),
            "HVD_TPU_COORD_ADDR": coord,
            "HVD_TPU_COORD_PORT": str(coord_port),
            "HOROVOD_HOSTNAME": socket.gethostname(),
        })
        ctx.barrier()
        f, a, k = cloudpickle.loads(payload)
        import horovod_tpu as hvd
        hvd.init()
        result = f(*a, **k)
        hvd.shutdown()
        return [(rank, cloudpickle.dumps(result))]

    rdd = sc.parallelize(range(num_proc), num_proc).barrier()
    gathered = rdd.mapPartitions(task).collect()
    out: List[Any] = [None] * num_proc
    for rank, blob in gathered:
        out[rank] = cloudpickle.loads(blob)
    return out
