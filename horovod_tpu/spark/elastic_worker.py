"""Worker entry for ``horovod_tpu.spark.run_elastic``: fetch the pickled
training fn from the driver KV, run it, publish this rank's result
(reference analog: ``spark/task/__init__.py`` exec of the pickled fn in
the task process)."""

from __future__ import annotations

import os
import sys


def main() -> int:
    import cloudpickle
    from horovod_tpu.runner.http_kv import kv_get, kv_put

    import socket
    addr, port = os.environ["HVD_SPARK_KV"].rsplit(":", 1)
    if socket.gethostname() == addr.split(".")[0]:
        addr = "127.0.0.1"  # same-box fast path, mirrors the agent loop
    payload = kv_get(addr, int(port), "payload", "fn")
    if payload is None:
        print("elastic_worker: no payload published", file=sys.stderr)
        return 1
    fn, args, kwargs = cloudpickle.loads(payload)
    result = fn(*args, **kwargs)
    kv_put(addr, int(port), "result", os.environ["HOROVOD_RANK"],
           cloudpickle.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
