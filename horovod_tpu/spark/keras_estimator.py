"""Keras backend for the Spark estimator API.

Reference: ``horovod/spark/keras/estimator.py`` (581 LoC: KerasEstimator
serializing the compiled model, remote.py training loop with hvd.keras) —
rebuilt on this package's keras adapter: the model travels as
architecture-JSON + weight arrays through the Store, each worker compiles
with ``hvd.keras.DistributedOptimizer`` and trains its rank's shard, and
rank 0 checkpoints the final weights back to the Store.
"""

from __future__ import annotations

import json
import pickle
from typing import Callable

import numpy as np

from horovod_tpu.spark.estimator import (HorovodEstimator, HorovodModel,
                                         load_transform, read_shard,
                                         xy_arrays)


def _save_keras(store, ckpt_dir: str, model, tag: str,
                arch: str = None) -> None:
    # arch override: the trained model is compiled with the dynamic
    # DistributedOptimizer subclass, whose compile config would not
    # deserialize — persist weights against the original UNCOMPILED
    # architecture instead
    spec = dict(arch=arch if arch is not None else model.to_json(),
                weights=[np.asarray(w) for w in model.get_weights()])
    store.write(store.join(ckpt_dir, f"{tag}.pkl"), pickle.dumps(spec))


def _load_keras(store, ckpt_dir: str, tag: str, custom_objects):
    """Returns (model, arch_json) from one deserialization."""
    import tensorflow as tf
    spec = pickle.loads(store.read(store.join(ckpt_dir, f"{tag}.pkl")))
    model = tf.keras.models.model_from_json(
        spec["arch"], custom_objects=custom_objects or {})
    model.set_weights(spec["weights"])
    return model, spec["arch"]


class KerasModel(HorovodModel):
    """Reference: ``KerasModel`` (``spark/keras/estimator.py``)."""

    def __init__(self, **kwargs) -> None:
        self._custom_objects = kwargs.pop("custom_objects", {})
        super().__init__(**kwargs)

    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self._model.predict(X, verbose=0))


class KerasEstimator(HorovodEstimator):
    """Reference: ``KerasEstimator`` (``spark/keras/estimator.py``).

    ``model`` is an UNCOMPILED tf.keras model; ``optimizer`` a keras
    optimizer name (e.g. ``"sgd"``), ``loss`` a keras loss name.
    """

    def _save_model_spec(self, ckpt_dir: str) -> None:
        store = self._store
        _save_keras(store, ckpt_dir, self._model, "initial")
        store.write(store.join(ckpt_dir, "train_spec.json"), json.dumps(
            dict(optimizer=self._optimizer or "sgd",
                 learning_rate=self._learning_rate,
                 loss=self._loss or "mse",
                 metrics=list(self._metrics or []),
                 feature_cols=list(self._feature_cols),
                 label_cols=list(self._label_cols),
                 batch_size=self._batch_size,
                 epochs=self._epochs,
                 sample_weight_col=self._sample_weight_col,
                 train_steps_per_epoch=self._train_steps_per_epoch,
                 validation_steps_per_epoch=self
                 ._validation_steps_per_epoch,
                 verbose=self._verbose)).encode())

    def _make_remote_fn(self, ckpt_dir: str, train_path: str,
                        val_path: str) -> Callable:
        custom_objects = self._custom_objects
        user_callbacks = list(self._callbacks or [])  # cloudpickled along
        store = self._store  # pickled into the worker closure

        def remote_train():
            import tensorflow as tf
            import horovod_tpu.keras as hvd_keras
            import horovod_tpu as hvd

            spec = json.loads(store.read_text(
                store.join(ckpt_dir, "train_spec.json")))
            model, initial_arch = _load_keras(store, ckpt_dir, "initial",
                                              custom_objects)
            opt = tf.keras.optimizers.get(
                {"class_name": spec["optimizer"],
                 "config": {"learning_rate":
                            spec["learning_rate"] * hvd.size()}})
            model.compile(
                optimizer=hvd_keras.DistributedOptimizer(opt),
                loss=spec["loss"], metrics=spec["metrics"])

            transform = load_transform(store, ckpt_dir)
            pdf = read_shard(store, train_path, hvd.rank(), hvd.size(),
                             transform=transform)
            X, Y = xy_arrays(pdf, spec["feature_cols"], spec["label_cols"])
            sample_weight = None
            if spec.get("sample_weight_col"):
                sample_weight = pdf[spec["sample_weight_col"]].to_numpy(
                    dtype=np.float32)
            val = None
            if val_path:
                vpdf = read_shard(store, val_path, 0, 1,
                                  transform=transform)
                vX, vY = xy_arrays(vpdf, spec["feature_cols"],
                                   spec["label_cols"])
                val = (vX, vY)
            cb = [hvd_keras.callbacks.BroadcastGlobalVariablesCallback(0),
                  hvd_keras.callbacks.MetricAverageCallback()]
            cb += user_callbacks  # reference: the callbacks param rides
            # along after the distributed ones (spark/keras/estimator.py)
            hist = model.fit(X, Y, batch_size=spec["batch_size"],
                             epochs=spec["epochs"], validation_data=val,
                             sample_weight=sample_weight,
                             steps_per_epoch=spec.get(
                                 "train_steps_per_epoch"),
                             validation_steps=spec.get(
                                 "validation_steps_per_epoch")
                             if val is not None else None,
                             verbose=spec["verbose"] if hvd.rank() == 0
                             else 0, callbacks=cb)
            if hvd.rank() == 0:
                _save_keras(store, ckpt_dir, model, "final",
                            arch=initial_arch)
            return {k: [float(x) for x in v]
                    for k, v in hist.history.items()}

        return remote_train

    def _load_trained_model(self, ckpt_dir: str) -> KerasModel:
        model, _ = _load_keras(self._store, ckpt_dir, "final",
                               self._custom_objects)
        return KerasModel(model=model, feature_cols=self._feature_cols,
                          label_cols=self._label_cols,
                          custom_objects=self._custom_objects,
                          run_id=self._run_id)
