"""Drop-in module path alias: ``horovod.spark.torch`` →
``horovod_tpu.spark.torch``(reference: ``horovod/spark/torch/__init__.py``
re-exporting TorchEstimator/TorchModel)."""

from horovod_tpu.spark.torch_estimator import (  # noqa: F401
    TorchEstimator, TorchModel)
