"""Storage abstraction for checkpoints / train-data paths.

Reference: ``horovod/spark/common/store.py:36-530`` — ``Store`` with
``LocalStore``/``HDFSStore``/``DBFSLocalStore`` and fsspec-backed remote
paths, used by the estimators for Parquet data + checkpoints. Here the same
surface over local paths and (when fsspec is importable) any fsspec URL;
TPU-native checkpointing prefers orbax through :func:`checkpoint_handler`.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class Store:
    """Base interface (reference: ``Store:36-140``)."""

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        raise NotImplementedError

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_logs_path(self, run_id: str) -> str:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def join(self, path: str, *parts: str) -> str:
        """Path join in the store's own path algebra — estimator code must
        never use ``os.path`` on store paths (they may be object-store
        URLs)."""
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        """Ensure a directory exists (no-op on keyspace-only backends)."""
        raise NotImplementedError

    def ls(self, path: str) -> list:
        """Entries directly under ``path`` (full store paths, sorted);
        ``[]`` for a missing directory. Used for shard discovery."""
        raise NotImplementedError

    def delete(self, path: str) -> None:
        """Remove a file; silently ignore a missing one. Used to clear
        stale shards when a run_id is reused."""
        raise NotImplementedError

    def read_text(self, path: str) -> str:
        return self.read(path).decode()

    @staticmethod
    def create(prefix_path: str, *args, **kwargs) -> "Store":
        """Reference: ``Store.create`` dispatch by URL scheme."""
        if "://" in prefix_path and not prefix_path.startswith("file://"):
            return FilesystemStore(prefix_path, *args, **kwargs)
        return LocalStore(prefix_path.replace("file://", ""), *args,
                          **kwargs)


class LocalStore(Store):
    """Local-filesystem store (reference: ``LocalStore:143-220``)."""

    def __init__(self, prefix_path: str) -> None:
        self._prefix = prefix_path
        os.makedirs(prefix_path, exist_ok=True)

    def _join(self, *parts: str) -> str:
        p = self.join(self._prefix, *parts)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        return p

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return self._join("intermediate_train_data" + (f".{idx}" if idx
                                                       else ""))

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return self._join("intermediate_val_data" + (f".{idx}" if idx
                                                     else ""))

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._join("runs", run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._join("runs", run_id, "logs")

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def join(self, path: str, *parts: str) -> str:
        return os.path.join(path, *parts)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def ls(self, path: str) -> list:
        if not os.path.isdir(path):
            return []
        return sorted(os.path.join(path, p) for p in os.listdir(path))

    def delete(self, path: str) -> None:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass


class FilesystemStore(Store):
    """fsspec-backed store for s3://, gs://, hdfs:// URLs (reference:
    ``FilesystemStore``/``HDFSStore``; fsspec is the modern superset).

    ``fs`` injects a ready filesystem instance (tests use a
    ``DirFileSystem`` faking a remote scheme; estimator workers receive
    the store by pickle, so the fs must be picklable — fsspec filesystems
    reconstruct from their storage options)."""

    def __init__(self, prefix_path: str, fs=None) -> None:
        try:
            import fsspec
        except ImportError as e:
            raise ImportError(
                f"FilesystemStore({prefix_path!r}) requires fsspec, which "
                "is not installed; use LocalStore or install fsspec.") from e
        if fs is not None:
            self._fs, self._prefix = fs, prefix_path
        else:
            self._fs, self._prefix = fsspec.core.url_to_fs(prefix_path)

    def _join(self, *parts: str) -> str:
        return self.join(self._prefix, *parts)

    def get_train_data_path(self, idx: Optional[str] = None) -> str:
        return self._join("intermediate_train_data" + (f".{idx}" if idx
                                                       else ""))

    def get_val_data_path(self, idx: Optional[str] = None) -> str:
        return self._join("intermediate_val_data" + (f".{idx}" if idx
                                                     else ""))

    def get_checkpoint_path(self, run_id: str) -> str:
        return self._join("runs", run_id, "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return self._join("runs", run_id, "logs")

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def read(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def join(self, path: str, *parts: str) -> str:
        return "/".join([path.rstrip("/")] + list(parts))

    def makedirs(self, path: str) -> None:
        try:
            self._fs.makedirs(path, exist_ok=True)
        except NotImplementedError:
            pass  # keyspace-only backend (e.g. s3): directories are implied

    def ls(self, path: str) -> list:
        try:
            return sorted(self._fs.ls(path, detail=False))
        except FileNotFoundError:
            return []

    def delete(self, path: str) -> None:
        try:
            self._fs.rm(path)
        except FileNotFoundError:
            pass


def checkpoint_handler(store: Store, run_id: str):
    """Orbax checkpointer rooted at the store's checkpoint path (TPU-native
    replacement for the estimators' keras/torch checkpoint files)."""
    import orbax.checkpoint as ocp
    path = store.get_checkpoint_path(run_id)
    return ocp.PyTreeCheckpointer(), path
