"""Drop-in module path alias: ``horovod.spark.keras`` →
``horovod_tpu.spark.keras`` (reference: ``horovod/spark/keras/__init__.py``
re-exporting KerasEstimator/KerasModel)."""

from horovod_tpu.spark.keras_estimator import (  # noqa: F401
    KerasEstimator, KerasModel)
