"""Spark ML estimator API: fit a model on a DataFrame via distributed
training, get back a model that transforms DataFrames.

Reference: ``horovod/spark/common/estimator.py:25-110`` (HorovodEstimator /
HorovodModel and their Params) with the Keras/Torch backends
(``spark/keras/estimator.py``, ``spark/torch/estimator.py``). TPU-native
redesign: data is materialized through the :class:`Store` as parquet,
training runs under the horovod_tpu launcher (``runner.run`` locally, the
Spark barrier runner on a cluster), and each worker reads its shard by
rank — no Petastorm dependency.

DataFrame duck-typing: anything with ``toPandas()`` (a Spark DataFrame) or
a pandas DataFrame directly, so the estimators are fully usable and
testable without a Spark session.
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from horovod_tpu.spark.store import LocalStore, Store


def _to_pandas(df):
    if hasattr(df, "toPandas"):
        return df.toPandas()
    return df


class Params:
    """Getter/setter param surface (reference: the Params mixins in
    ``spark/common/params.py`` — ``setX``/``getX`` returning self)."""

    _param_names: Sequence[str] = ()

    def _init_params(self, values: Dict[str, Any]) -> None:
        for k in self._param_names:
            setattr(self, "_" + k, values.get(k))

    def __getattr__(self, item):
        # setEpochs / getEpochs style accessors, generated from param names
        if item.startswith(("set", "get")) and len(item) > 3:
            name = item[3].lower() + item[4:]
            # translate camelCase -> snake_case
            snake = "".join("_" + c.lower() if c.isupper() else c
                            for c in name)
            if snake in self._param_names:
                if item.startswith("set"):
                    def setter(value):
                        setattr(self, "_" + snake, value)
                        return self
                    return setter
                return lambda: getattr(self, "_" + snake)
        raise AttributeError(item)


class HorovodModel(Params):
    """Trained model wrapper (reference: ``HorovodModel``,
    ``spark/common/estimator.py:79-110``)."""

    _param_names = ("model", "feature_cols", "label_cols", "output_cols",
                    "run_id")

    def __init__(self, **kwargs) -> None:
        self._init_params(kwargs)

    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, df):
        """Append prediction columns to the DataFrame (reference:
        ``HorovodModel.transform``). Returns a pandas DataFrame."""
        pdf = _to_pandas(df).copy()
        X = np.stack([pdf[c].to_numpy(dtype=np.float32)
                      for c in self._feature_cols], axis=1)
        preds = np.asarray(self._predict_batch(X))
        out_cols = self._output_cols or \
            [f"{c}__output" for c in self._label_cols]
        if preds.ndim == 1:
            preds = preds[:, None]
        for i, c in enumerate(out_cols):
            pdf[c] = preds[:, i] if preds.shape[1] > i else preds[:, -1]
        return pdf


class HorovodEstimator(Params):
    """Distributed-training estimator (reference: ``HorovodEstimator``,
    ``spark/common/estimator.py:25-78``)."""

    _param_names = ("num_proc", "model", "store", "optimizer", "loss",
                    "metrics", "feature_cols", "label_cols", "validation",
                    "batch_size", "epochs", "verbose", "run_id",
                    "callbacks", "custom_objects", "shuffle",
                    "learning_rate")

    def __init__(self, **kwargs) -> None:
        defaults = dict(num_proc=1, metrics=[], validation=None,
                        batch_size=32, epochs=1, verbose=1, shuffle=True,
                        callbacks=[], custom_objects={},
                        learning_rate=1e-3)
        defaults.update(kwargs)
        self._init_params(defaults)
        if self._store is None:
            self._store = LocalStore.create(
                os.path.join(os.path.expanduser("~"), ".hvd_tpu_store"))

    # -- backend hooks -------------------------------------------------------
    def _save_model_spec(self, ckpt_dir: str) -> None:
        raise NotImplementedError

    def _make_remote_fn(self, ckpt_dir: str, train_path: str,
                        val_path: str) -> Callable:
        raise NotImplementedError

    def _load_trained_model(self, ckpt_dir: str) -> HorovodModel:
        raise NotImplementedError

    # -- fit -----------------------------------------------------------------
    def fit(self, df) -> HorovodModel:
        """Materialize data through the Store, train under the launcher,
        return the trained model (reference: ``Estimator.fit``)."""
        run_id = self._run_id or f"run_{uuid.uuid4().hex[:8]}"
        self._run_id = run_id
        store: Store = self._store
        pdf = _to_pandas(df)
        if self._shuffle:
            pdf = pdf.sample(frac=1.0, random_state=0).reset_index(
                drop=True)
        val_pdf = None
        if isinstance(self._validation, float) and self._validation > 0:
            n_val = max(1, int(len(pdf) * self._validation))
            val_pdf, pdf = pdf.iloc[:n_val], pdf.iloc[n_val:]
        elif isinstance(self._validation, str):
            mask = pdf[self._validation].astype(bool)
            val_pdf, pdf = pdf[mask], pdf[~mask]

        # ALL artifact IO goes through the Store's path algebra + byte API
        # so gs://-class object stores work identically to local paths
        # (reference: store.py:36-530 — estimators read/write exclusively
        # through the Store)
        train_path = store.get_train_data_path(run_id)
        val_path = store.get_val_data_path(run_id)
        store.makedirs(train_path)
        store.write(store.join(train_path, "data.parquet"),
                    _parquet_bytes(pdf.reset_index(drop=True)))
        if val_pdf is not None and len(val_pdf):
            store.makedirs(val_path)
            store.write(store.join(val_path, "data.parquet"),
                        _parquet_bytes(val_pdf.reset_index(drop=True)))
        else:
            val_path = ""

        ckpt_dir = store.get_checkpoint_path(run_id)
        store.makedirs(ckpt_dir)
        self._save_model_spec(ckpt_dir)

        remote = self._make_remote_fn(ckpt_dir, train_path, val_path)
        in_spark = False
        try:
            from pyspark.sql import SparkSession
            in_spark = SparkSession.getActiveSession() is not None
        except Exception:
            pass
        if in_spark:
            from horovod_tpu.spark import run as spark_run
            histories = spark_run(remote, num_proc=self._num_proc)
        else:
            from horovod_tpu.runner import run as local_run
            histories = local_run(remote, np=self._num_proc)

        model = self._load_trained_model(ckpt_dir)
        model.history = histories[0]
        return model


def _parquet_bytes(pdf) -> bytes:
    import io
    buf = io.BytesIO()
    pdf.to_parquet(buf)
    return buf.getvalue()


def read_shard(store: Store, data_path: str, rank: int, size: int):
    """Worker-side shard read through the Store: rows [rank::size] of the
    materialized parquet (the reference partitions Petastorm row groups
    per rank). The store travels to the worker by pickle, so remote
    backends reconnect there."""
    import io

    import pandas as pd
    pdf = pd.read_parquet(
        io.BytesIO(store.read(store.join(data_path, "data.parquet"))))
    return pdf.iloc[rank::size].reset_index(drop=True)


def xy_arrays(pdf, feature_cols: Sequence[str], label_cols: Sequence[str]):
    X = np.stack([pdf[c].to_numpy(dtype=np.float32)
                  for c in feature_cols], axis=1)
    Y = np.stack([pdf[c].to_numpy(dtype=np.float32)
                  for c in label_cols], axis=1)
    return X, Y
