"""Spark ML estimator API: fit a model on a DataFrame via distributed
training, get back a model that transforms DataFrames.

Reference: ``horovod/spark/common/estimator.py:25-110`` (HorovodEstimator /
HorovodModel and their Params) with the Keras/Torch backends
(``spark/keras/estimator.py``, ``spark/torch/estimator.py``). TPU-native
redesign: data is materialized through the :class:`Store` as parquet,
training runs under the horovod_tpu launcher (``runner.run`` locally, the
Spark barrier runner on a cluster), and each worker reads its shard by
rank — no Petastorm dependency.

DataFrame duck-typing: anything with ``toPandas()`` (a Spark DataFrame) or
a pandas DataFrame directly, so the estimators are fully usable and
testable without a Spark session.
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from horovod_tpu.spark.store import LocalStore, Store


def _to_pandas(df):
    if hasattr(df, "toPandas"):
        return df.toPandas()
    return df


def _by_value_pickler():
    """cloudpickle when available (serializes notebook/nested functions BY
    VALUE); stdlib pickle otherwise."""
    try:
        import cloudpickle
        return cloudpickle
    except ImportError:
        return pickle


def _split_frame(pdf, shuffle: bool, validation, seed: int,
                 min_one: bool = True):
    """THE split semantics, shared by both materialization paths:
    optional seeded shuffle, then either a float-fraction validation cut
    or a boolean-column selection. Returns ``(train_pdf,
    val_pdf_or_None)``.

    ``min_one`` floors a float-fraction cut at 1 row — right for the
    local path (the WHOLE dataset must yield a validation split when one
    was asked for), wrong per partition on the distributed path: a
    per-partition floor over many small partitions inflates
    ``validation=0.01`` far past 1% (each 20-row partition would donate
    a row = 5%), so that path passes ``min_one=False`` and lets the
    global fraction emerge from honest per-partition rounding."""
    if shuffle:
        pdf = pdf.sample(frac=1.0, random_state=seed)
    pdf = pdf.reset_index(drop=True)
    val_pdf = None
    if isinstance(validation, float) and validation > 0:
        n_val = int(round(len(pdf) * validation))
        if min_one:
            n_val = max(1, n_val)
        val_pdf, pdf = pdf.iloc[:n_val], pdf.iloc[n_val:]
    elif isinstance(validation, str):
        mask = pdf[validation].astype(bool)
        val_pdf, pdf = pdf[mask], pdf[~mask]
    if val_pdf is not None:
        val_pdf = val_pdf.reset_index(drop=True)
    return pdf.reset_index(drop=True), val_pdf


class Params:
    """Getter/setter param surface (reference: the Params mixins in
    ``spark/common/params.py`` — ``setX``/``getX`` returning self)."""

    _param_names: Sequence[str] = ()

    def _init_params(self, values: Dict[str, Any]) -> None:
        for k in self._param_names:
            setattr(self, "_" + k, values.get(k))

    def __getattr__(self, item):
        # setEpochs / getEpochs style accessors, generated from param names
        if item.startswith(("set", "get")) and len(item) > 3:
            name = item[3].lower() + item[4:]
            # translate camelCase -> snake_case
            snake = "".join("_" + c.lower() if c.isupper() else c
                            for c in name)
            if snake in self._param_names:
                if item.startswith("set"):
                    def setter(value):
                        setattr(self, "_" + snake, value)
                        return self
                    return setter
                return lambda: getattr(self, "_" + snake)
        raise AttributeError(item)


class HorovodModel(Params):
    """Trained model wrapper (reference: ``HorovodModel``,
    ``spark/common/estimator.py:79-110``)."""

    _param_names = ("model", "feature_cols", "label_cols", "output_cols",
                    "run_id")

    def __init__(self, **kwargs) -> None:
        self._init_params(kwargs)

    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, df):
        """Append prediction columns to the DataFrame (reference:
        ``HorovodModel.transform``). Returns a pandas DataFrame."""
        pdf = _to_pandas(df).copy()
        X = np.stack([pdf[c].to_numpy(dtype=np.float32)
                      for c in self._feature_cols], axis=1)
        preds = np.asarray(self._predict_batch(X))
        out_cols = self._output_cols or \
            [f"{c}__output" for c in self._label_cols]
        if preds.ndim == 1:
            preds = preds[:, None]
        for i, c in enumerate(out_cols):
            pdf[c] = preds[:, i] if preds.shape[1] > i else preds[:, -1]
        return pdf


class HorovodEstimator(Params):
    """Distributed-training estimator (reference: ``HorovodEstimator``,
    ``spark/common/estimator.py:25-78``)."""

    _param_names = ("num_proc", "model", "store", "optimizer", "loss",
                    "metrics", "feature_cols", "label_cols", "validation",
                    "batch_size", "epochs", "verbose", "run_id",
                    "callbacks", "custom_objects", "shuffle",
                    "learning_rate", "sample_weight_col",
                    "train_steps_per_epoch", "validation_steps_per_epoch",
                    "transformation_fn", "backward_passes_per_step")

    def __init__(self, **kwargs) -> None:
        defaults = dict(num_proc=1, metrics=[], validation=None,
                        batch_size=32, epochs=1, verbose=1, shuffle=True,
                        callbacks=[], custom_objects={},
                        learning_rate=1e-3, sample_weight_col=None,
                        train_steps_per_epoch=None,
                        validation_steps_per_epoch=None,
                        transformation_fn=None,
                        backward_passes_per_step=1)
        defaults.update(kwargs)
        self._init_params(defaults)
        if self._store is None:
            self._store = LocalStore.create(
                os.path.join(os.path.expanduser("~"), ".hvd_tpu_store"))

    # -- backend hooks -------------------------------------------------------
    def _validate_params(self) -> None:
        """Config errors detectable up front raise HERE, before any data
        is materialized or artifacts written (fail fast on a cluster)."""

    def _save_model_spec(self, ckpt_dir: str) -> None:
        raise NotImplementedError

    def _make_remote_fn(self, ckpt_dir: str, train_path: str,
                        val_path: str) -> Callable:
        raise NotImplementedError

    def _load_trained_model(self, ckpt_dir: str) -> HorovodModel:
        raise NotImplementedError

    # -- data materialization ------------------------------------------------
    def _materialize_pandas(self, pdf, store: "Store", train_path: str,
                            val_path: str) -> str:
        """Driver-local path (pandas input): one parquet per split."""
        pdf, val_pdf = _split_frame(pdf, self._shuffle, self._validation,
                                    seed=0)
        if not len(pdf):
            raise ValueError("DataFrame produced no training rows")
        store.makedirs(train_path)
        store.write(store.join(train_path, "data.parquet"),
                    _parquet_bytes(pdf))
        if val_pdf is not None and len(val_pdf):
            store.makedirs(val_path)
            store.write(store.join(val_path, "data.parquet"),
                        _parquet_bytes(val_pdf))
        else:
            val_path = ""
        return val_path

    def _materialize_distributed(self, df, store: "Store", train_path: str,
                                 val_path: str) -> str:
        """Spark path: EXECUTORS write one parquet shard per partition
        through the (pickled) Store — the dataset never moves through the
        driver (reference: ``spark/common/util.py`` prepare_data, which
        materializes via distributed ``df.write.parquet``; the previous
        ``toPandas()`` here collected everything to one node).

        Shuffle/validation-split happen per partition via
        :func:`_split_frame` (seeded by partition id): a float
        ``validation`` takes that fraction of each partition — globally
        equivalent to the reference's random-split semantics as long as
        partitions are not pathologically skewed. A string ``validation``
        selects rows where that boolean column is set, exactly as the
        reference does. A partition whose train split comes up empty
        writes NO shard (``read_shard`` falls back to row striping when
        shards are scarce, so no rank ends up with a poisoned 0-row
        file)."""
        shuffle, validation = self._shuffle, self._validation
        store.makedirs(train_path)
        store.makedirs(val_path)

        def write_partition(idx, row_iter):
            import pandas as pd
            rows = [r.asDict() for r in row_iter]
            if not rows:
                return iter([(idx, 0, 0)])
            pdf, val_pdf = _split_frame(pd.DataFrame(rows), shuffle,
                                        validation, seed=idx,
                                        min_one=False)
            if len(pdf):
                store.write(
                    store.join(train_path, f"part-{idx:05d}.parquet"),
                    _parquet_bytes(pdf))
            n_val_rows = 0
            if val_pdf is not None and len(val_pdf):
                store.write(
                    store.join(val_path, f"part-{idx:05d}.parquet"),
                    _parquet_bytes(val_pdf))
                n_val_rows = len(val_pdf)
            return iter([(idx, len(pdf), n_val_rows)])

        meta = df.rdd.mapPartitionsWithIndex(write_partition).collect()
        n_train = sum(m[1] for m in meta)
        n_val = sum(m[2] for m in meta)
        if n_train == 0:
            raise ValueError("DataFrame produced no training rows")
        if n_val == 0 and isinstance(validation, float) and validation > 0:
            # honest per-partition rounding (no 1-row floor) can land on
            # zero when every partition is tiny relative to the fraction;
            # don't silently train without the requested validation set
            from horovod_tpu.common.logging import get_logger
            get_logger().warning(
                "validation=%s yielded 0 rows across %d partitions "
                "(partitions too small for the fraction); training "
                "proceeds WITHOUT a validation set — repartition the "
                "DataFrame or raise the fraction", validation, len(meta))
        return val_path if n_val else ""

    # -- fit -----------------------------------------------------------------
    def fit(self, df) -> HorovodModel:
        """Materialize data through the Store, train under the launcher,
        return the trained model (reference: ``Estimator.fit``)."""
        self._validate_params()
        # serialize the transformation up front: an unpicklable closure
        # must fail in seconds, not after a full-dataset materialization
        transform_bytes = _by_value_pickler().dumps(
            self._transformation_fn)
        run_id = self._run_id or f"run_{uuid.uuid4().hex[:8]}"
        self._run_id = run_id
        store: Store = self._store
        # ALL artifact IO goes through the Store's path algebra + byte API
        # so gs://-class object stores work identically to local paths
        # (reference: store.py:36-530 — estimators read/write exclusively
        # through the Store)
        train_path = store.get_train_data_path(run_id)
        val_path = store.get_val_data_path(run_id)
        # a reused run_id must not leave stale shards behind: read_shard
        # globs the whole directory, so leftovers from a previous fit
        # (different partition count, or the single-parquet pandas path)
        # would silently mix into this run's data
        for stale in store.ls(train_path) + store.ls(val_path):
            store.delete(stale)
        if hasattr(df, "rdd"):  # a Spark DataFrame: executors materialize
            val_path = self._materialize_distributed(
                df, store, train_path, val_path)
        else:
            val_path = self._materialize_pandas(
                _to_pandas(df), store, train_path, val_path)

        ckpt_dir = store.get_checkpoint_path(run_id)
        store.makedirs(ckpt_dir)
        self._save_model_spec(ckpt_dir)
        # transformation_fn: fn(pdf) -> pdf applied to every worker's
        # shard before train AND validation (reference: the param of the
        # same name, spark/common/params.py); serialized above, fail-fast
        store.write(store.join(ckpt_dir, "transform.pkl"),
                    transform_bytes)

        remote = self._make_remote_fn(ckpt_dir, train_path, val_path)
        in_spark = False
        try:
            from pyspark.sql import SparkSession
            in_spark = SparkSession.getActiveSession() is not None
        except Exception:
            pass
        if in_spark:
            from horovod_tpu.spark import run as spark_run
            histories = spark_run(remote, num_proc=self._num_proc)
        else:
            from horovod_tpu.runner import run as local_run
            histories = local_run(remote, np=self._num_proc)

        model = self._load_trained_model(ckpt_dir)
        model.history = histories[0]
        return model


def _parquet_bytes(pdf) -> bytes:
    import io
    buf = io.BytesIO()
    pdf.to_parquet(buf)
    return buf.getvalue()


def load_transform(store: Store, ckpt_dir: str):
    """Worker-side: the estimator's transformation_fn (or None)."""
    return pickle.loads(store.read(store.join(ckpt_dir, "transform.pkl")))


def read_shard(store: Store, data_path: str, rank: int, size: int,
               transform=None):
    """Worker-side shard read through the Store (the reference partitions
    Petastorm row groups per rank). The store travels to the worker by
    pickle, so remote backends reconnect there. ``transform`` (the
    estimator's transformation_fn) is applied to the shard before it is
    returned — ONE site, so train/val and keras/torch can't drift.

    With at least ``size`` part files (the distributed materialization
    writes one per DataFrame partition), files are assigned round-robin
    by rank — each worker reads ONLY its own shards. With fewer files
    (the driver-local single-parquet path), every worker reads the file
    set and takes rows ``[rank::size]``."""
    import io

    import pandas as pd
    files = [p for p in store.ls(data_path) if p.endswith(".parquet")]
    if not files:
        raise FileNotFoundError(f"no parquet shards under {data_path}")

    def load(paths):
        frames = [pd.read_parquet(io.BytesIO(store.read(p)))
                  for p in paths]
        return frames[0] if len(frames) == 1 else pd.concat(
            frames, ignore_index=True)

    if len(files) >= size:
        pdf = load(files[rank::size]).reset_index(drop=True)
    else:
        pdf = load(files).iloc[rank::size].reset_index(drop=True)
    if transform is not None:
        pdf = transform(pdf).reset_index(drop=True)
    return pdf


def xy_arrays(pdf, feature_cols: Sequence[str], label_cols: Sequence[str]):
    X = np.stack([pdf[c].to_numpy(dtype=np.float32)
                  for c in feature_cols], axis=1)
    Y = np.stack([pdf[c].to_numpy(dtype=np.float32)
                  for c in label_cols], axis=1)
    return X, Y
