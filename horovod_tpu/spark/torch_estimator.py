"""Torch backend for the Spark estimator API.

Reference: ``horovod/spark/torch/estimator.py`` (506 LoC: TorchEstimator
serializing the model/optimizer/loss, remote.py loop with hvd.torch) —
rebuilt on this package's torch adapter: the model travels as a pickled
module + state_dict through the Store, each worker wraps its optimizer in
``horovod_tpu.torch.DistributedOptimizer`` and trains its rank's shard,
and rank 0 checkpoints the final state back to the Store.
"""

from __future__ import annotations

import json
import pickle
from typing import Callable

import numpy as np

from horovod_tpu.spark.estimator import (HorovodEstimator, HorovodModel,
                                         load_transform, read_shard,
                                         xy_arrays)


class TorchModel(HorovodModel):
    """Reference: ``TorchModel`` (``spark/torch/estimator.py``)."""

    def _predict_batch(self, X: np.ndarray) -> np.ndarray:
        import torch
        self._model.eval()
        with torch.no_grad():
            return self._model(torch.from_numpy(X)).numpy()


class TorchEstimator(HorovodEstimator):
    """Reference: ``TorchEstimator`` (``spark/torch/estimator.py``).

    ``model`` is a ``torch.nn.Module``; ``optimizer`` an optimizer NAME
    from ``torch.optim`` (e.g. ``"SGD"``); ``loss`` a callable
    ``loss(pred, target)`` or a ``torch.nn`` loss name (e.g.
    ``"MSELoss"``).
    """

    def _loss_value(self):
        """The effective loss param (single source of the default)."""
        return self._loss if self._loss is not None else "MSELoss"

    def _validate_params(self) -> None:
        if self._sample_weight_col and \
                not isinstance(self._loss_value(), str):
            raise ValueError(
                "sample_weight_col needs a NAMED torch loss (it is "
                "rebuilt with reduction='none' on the workers); weight "
                "inside your custom loss callable instead")

    def _save_model_spec(self, ckpt_dir: str) -> None:
        store = self._store
        store.write(store.join(ckpt_dir, "initial.pkl"),
                    pickle.dumps(self._model))
        loss_value = self._loss_value()
        loss = loss_value if isinstance(loss_value, str) else None
        store.write(store.join(ckpt_dir, "loss.pkl"),
                    pickle.dumps(loss_value if loss is None else None))
        # metrics: callables metric(pred, target) -> scalar, evaluated per
        # epoch on the worker's shard and rank-averaged (reference:
        # spark/torch/estimator.py metrics param + remote.py aggregation).
        # cloudpickle serializes them BY VALUE, so user-module / notebook
        # functions survive the trip to worker processes.
        from horovod_tpu.spark.estimator import _by_value_pickler
        store.write(store.join(ckpt_dir, "metrics.pkl"),
                    _by_value_pickler().dumps(list(self._metrics or [])))
        store.write(store.join(ckpt_dir, "train_spec.json"), json.dumps(
            dict(optimizer=self._optimizer or "SGD",
                 learning_rate=self._learning_rate,
                 loss_name=loss,
                 feature_cols=list(self._feature_cols),
                 label_cols=list(self._label_cols),
                 batch_size=self._batch_size,
                 epochs=self._epochs,
                 sample_weight_col=self._sample_weight_col,
                 train_steps_per_epoch=self._train_steps_per_epoch,
                 validation_steps_per_epoch=self
                 ._validation_steps_per_epoch,
                 backward_passes_per_step=self._backward_passes_per_step,
                 verbose=self._verbose)).encode())

    def _make_remote_fn(self, ckpt_dir: str, train_path: str,
                        val_path: str) -> Callable:
        store = self._store  # pickled into the worker closure

        def remote_train():
            import torch
            import horovod_tpu.torch as thvd
            import horovod_tpu as hvd

            spec = json.loads(store.read_text(
                store.join(ckpt_dir, "train_spec.json")))
            model = pickle.loads(store.read(
                store.join(ckpt_dir, "initial.pkl")))
            weight_col = spec.get("sample_weight_col")
            eval_loss_fn = None
            if spec["loss_name"]:
                # validation stays UNWEIGHTED (reference semantics:
                # sample weights shape training only)
                eval_loss_fn = getattr(torch.nn, spec["loss_name"])()
                if weight_col:
                    # per-row losses, weighted mean below (reference:
                    # torch estimator sample_weight_col)
                    per_row = getattr(torch.nn, spec["loss_name"])(
                        reduction="none")

                    def loss_fn(pred, target, w):
                        r = per_row(pred, target)
                        r = r.reshape(r.shape[0], -1).mean(dim=1)
                        return (r * w).sum() / w.sum().clamp_min(1e-12)
                else:
                    loss_fn = eval_loss_fn
            else:
                loss_fn = pickle.loads(store.read(
                    store.join(ckpt_dir, "loss.pkl")))
            if eval_loss_fn is None:
                eval_loss_fn = loss_fn
            metric_fns = pickle.loads(store.read(
                store.join(ckpt_dir, "metrics.pkl")))
            opt_cls = getattr(torch.optim, spec["optimizer"])
            bpps = max(1, int(spec.get("backward_passes_per_step") or 1))
            opt = thvd.DistributedOptimizer(
                opt_cls(model.parameters(),
                        lr=spec["learning_rate"] * hvd.size()),
                named_parameters=model.named_parameters(),
                backward_passes_per_step=bpps)
            thvd.broadcast_parameters(model.state_dict(), root_rank=0)
            thvd.broadcast_optimizer_state(opt, root_rank=0)

            transform = load_transform(store, ckpt_dir)
            pdf = read_shard(store, train_path, hvd.rank(), hvd.size(),
                             transform=transform)
            X, Y = xy_arrays(pdf, spec["feature_cols"], spec["label_cols"])
            X_t = torch.from_numpy(X)
            Y_t = torch.from_numpy(Y)
            W_t = torch.from_numpy(pdf[weight_col].to_numpy(
                dtype=np.float32)) if weight_col else None
            val = None
            if val_path:
                vpdf = read_shard(store, val_path, 0, 1,
                                  transform=transform)
                vX, vY = xy_arrays(vpdf, spec["feature_cols"],
                                   spec["label_cols"])
                val = (torch.from_numpy(vX), torch.from_numpy(vY))
            def metric_name(i, fn):
                return getattr(fn, "__name__", None) or f"metric_{i}"

            bs = spec["batch_size"]
            # optional per-epoch step caps (reference:
            # train_steps_per_epoch / validation_steps_per_epoch). The
            # train window ROTATES through the shard across epochs, like
            # a dataloader that keeps advancing — a fixed prefix would
            # silently never train the tail rows.
            n_train = len(X_t)
            if spec.get("train_steps_per_epoch"):
                n_train = min(n_train,
                              spec["train_steps_per_epoch"] * bs)
            if val is not None and spec.get("validation_steps_per_epoch"):
                cap = spec["validation_steps_per_epoch"] * bs
                val = (val[0][:cap], val[1][:cap])

            def epoch_window(epoch):
                if n_train == len(X_t):
                    return X_t, Y_t, W_t
                idx = (torch.arange(n_train)
                       + epoch * n_train) % len(X_t)
                return (X_t[idx], Y_t[idx],
                        W_t[idx] if W_t is not None else None)

            history = {"loss": []}
            for i, fn in enumerate(metric_fns):
                history[metric_name(i, fn)] = []
            if val is not None:
                history["val_loss"] = []
            # with gradient accumulation, only FULL k-backward groups
            # step (a trailing partial group would leave hook enqueues
            # mid-countdown across the epoch boundary)
            batch_starts = list(range(0, n_train, bs))
            if bpps > 1:
                batch_starts = batch_starts[
                    :(len(batch_starts) // bpps) * bpps]
            for epoch in range(spec["epochs"]):
                model.train()
                losses = []
                Xe, Ye, We = epoch_window(epoch)
                opt.zero_grad()
                for k, i in enumerate(batch_starts, start=1):
                    pred = model(Xe[i:i + bs])
                    if We is not None:
                        loss = loss_fn(pred, Ye[i:i + bs],
                                       We[i:i + bs])
                    else:
                        loss = loss_fn(pred, Ye[i:i + bs])
                    loss.backward()
                    if k % bpps == 0:
                        opt.step()
                        opt.zero_grad()
                    losses.append(float(loss.detach()))
                # epoch loss averaged across workers, WEIGHTED by batch
                # count, so an unequal (or empty) shard can't poison the
                # mean with a NaN (reference: remote.py metric
                # aggregation)
                sums = np.asarray(thvd.allreduce(
                    torch.tensor([float(np.sum(losses)),
                                  float(len(losses))]),
                    op=thvd.Sum, name=f"ep.{epoch}"))
                mean = float(sums[0] / sums[1]) if sums[1] else 0.0
                history["loss"].append(mean)
                if metric_fns:
                    model.eval()
                    with torch.no_grad():
                        pred = model(X_t)
                    for i, fn in enumerate(metric_fns):
                        m = float(fn(pred, Y_t))
                        m = float(np.asarray(thvd.allreduce(
                            torch.tensor([m]), op=thvd.Average,
                            name=f"ep.{epoch}.m{i}"))[0])
                        history[metric_name(i, fn)].append(m)
                if val is not None:
                    model.eval()
                    with torch.no_grad():
                        vloss = float(eval_loss_fn(model(val[0]), val[1]))
                    history["val_loss"].append(vloss)
                if spec["verbose"] and hvd.rank() == 0:
                    print(f"[torch-estimator] epoch {epoch}: loss={mean}",
                          flush=True)
            if hvd.rank() == 0:
                store.write(store.join(ckpt_dir, "final.pkl"),
                            pickle.dumps(model))
            return history

        return remote_train

    def _load_trained_model(self, ckpt_dir: str) -> TorchModel:
        model = pickle.loads(self._store.read(
            self._store.join(ckpt_dir, "final.pkl")))
        return TorchModel(model=model, feature_cols=self._feature_cols,
                          label_cols=self._label_cols,
                          run_id=self._run_id)
