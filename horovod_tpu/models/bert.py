"""BERT-Large — the second headline benchmark family
(reference: BASELINE "BERT-Large pretraining (PyTorch DistributedOptimizer +
fp16 compression)"; the reference has no model zoo — users bring torch/TF
BERT and wrap its optimizer).

TPU-native: a flax encoder in bf16 with fp32 layernorms, MLM + NSP heads,
trained in GSPMD-auto mode — batch over data axes, optionally tensor-
parallel via logical axis annotations (``nn.with_partitioning``) so heads /
mlp shard over ``tp`` when the mesh has one.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from horovod_tpu.models.scan_util import multi_step
import flax.linen as nn
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 1024          # BERT-Large
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_position: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.bfloat16


def bert_large(dtype=jnp.bfloat16) -> "BertConfig":
    return BertConfig(dtype=dtype)


def bert_base(dtype=jnp.bfloat16) -> "BertConfig":
    return BertConfig(hidden_size=768, num_layers=12, num_heads=12,
                      intermediate_size=3072, dtype=dtype)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        c = self.cfg
        head_dim = c.hidden_size // c.num_heads
        dense = lambda name: nn.DenseGeneral(
            (c.num_heads, head_dim), dtype=c.dtype, name=name,
            kernel_init=nn.with_partitioning(
                nn.initializers.normal(0.02), (None, "tp", None)))
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
            jnp.asarray(head_dim, c.dtype))
        s = jnp.where(mask[:, None, None, :], s, -1e9)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(c.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        o = nn.DenseGeneral(c.hidden_size, axis=(-2, -1), dtype=c.dtype,
                            name="out",
                            kernel_init=nn.with_partitioning(
                                nn.initializers.normal(0.02),
                                ("tp", None, None)))(o)
        return o


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        c = self.cfg
        a = SelfAttention(c, name="attention")(x, mask)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_att")(x + a)
        h = nn.Dense(c.intermediate_size, dtype=c.dtype, name="ffn_in",
                     kernel_init=nn.with_partitioning(
                         nn.initializers.normal(0.02), (None, "tp")))(x)
        h = nn.gelu(h)
        h = nn.Dense(c.hidden_size, dtype=c.dtype, name="ffn_out",
                     kernel_init=nn.with_partitioning(
                         nn.initializers.normal(0.02), ("tp", None)))(h)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_ffn")(x + h)
        return x


class Bert(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, attention_mask):
        c = self.cfg
        emb = nn.Embed(c.vocab_size, c.hidden_size, dtype=c.dtype,
                       name="word_embeddings",
                       embedding_init=nn.with_partitioning(
                           nn.initializers.normal(0.02), ("tp", None)))
        x = emb(input_ids)
        pos = jnp.arange(input_ids.shape[1])[None]
        x = x + nn.Embed(c.max_position, c.hidden_size, dtype=c.dtype,
                         name="position_embeddings")(pos)
        x = x + nn.Embed(c.type_vocab_size, c.hidden_size, dtype=c.dtype,
                         name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_emb")(x)
        for i in range(c.num_layers):
            x = BertLayer(c, name=f"layer_{i}")(x, attention_mask)
        # MLM head (tied to word embeddings) + NSP head on [CLS]
        h = nn.Dense(c.hidden_size, dtype=c.dtype, name="mlm_transform")(x)
        h = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(nn.gelu(h))
        mlm_logits = emb.attend(h.astype(c.dtype)).astype(jnp.float32)
        nsp_logits = nn.Dense(2, dtype=jnp.float32, name="nsp")(
            x[:, 0].astype(jnp.float32))
        return mlm_logits, nsp_logits


def pretrain_loss(mlm_logits, nsp_logits, mlm_labels, mlm_mask, nsp_labels):
    """Masked-LM + next-sentence loss (standard BERT pretraining)."""
    v = mlm_logits.shape[-1]
    mlm = optax.softmax_cross_entropy(
        mlm_logits, jax.nn.one_hot(mlm_labels, v))
    denom = jnp.maximum(jnp.sum(mlm_mask), 1.0)
    mlm = jnp.sum(mlm * mlm_mask) / denom
    nsp = optax.softmax_cross_entropy(
        nsp_logits, jax.nn.one_hot(nsp_labels, 2)).mean()
    return mlm + nsp


def make_bert_train_step(model: Bert, optimizer, mesh: Mesh,
                         scan_steps: int = 1):
    """GSPMD-auto pretraining step; flax partitioning metadata shards the
    big matrices over ``tp`` while XLA handles dp gradient reduction.

    ``scan_steps > 1`` runs that many optimizer steps per call via
    ``lax.scan`` in ONE compiled program (one dispatch per chain; see
    ``make_resnet_train_step``). All scanned steps consume the SAME
    batch (``scan_util.multi_step`` same-batch semantics — a throughput
    construct, not multi-batch training). The returned loss is the last
    step's.

    ``params``/``opt_state`` buffers are DONATED (in-place update on
    device): keep only the returned state — the inputs are invalidated
    after the call on TPU."""

    def one_step(params, opt_state, batch):
        def loss_fn(p):
            mlm_logits, nsp_logits = model.apply(
                {"params": p}, batch["input_ids"], batch["token_type_ids"],
                batch["attention_mask"])
            return pretrain_loss(mlm_logits, nsp_logits,
                                 batch["mlm_labels"], batch["mlm_mask"],
                                 batch["nsp_labels"])
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    chain = multi_step(one_step, n_carry=2, scan_steps=scan_steps)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, batch):
        return chain(params, opt_state, batch)

    return step


def init_bert(model: Bert, rng_key, seq_len: int = 128, mesh: Mesh = None):
    """Initialize; apply flax logical partitioning onto the mesh's tp axis
    (replicated when tp is absent)."""
    dummy = jnp.zeros((1, seq_len), jnp.int32)
    variables = model.init(rng_key, dummy, dummy,
                           jnp.ones((1, seq_len), bool))
    params = variables["params"]
    if mesh is not None:
        import flax
        tp_live = mesh.shape.get("tp", 1) > 1

        def place(x):
            if isinstance(x, nn.Partitioned):
                spec = P(*x.names) if tp_live else P()
                arr = jax.device_put(x.value, NamedSharding(mesh, spec))
                return x.replace_boxed(arr)
            return jax.device_put(x, NamedSharding(mesh, P()))
        params = jax.tree_util.tree_map(
            place, params,
            is_leaf=lambda x: isinstance(x, nn.Partitioned))
    return params
