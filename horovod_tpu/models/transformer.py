"""Flagship model: GPT-style (optionally MoE) transformer with full 5-axis
parallelism — dp (batch), pp (stages), ep (experts), sp (sequence/ring
attention), tp (tensor) — written as ONE manual-SPMD program under
``shard_map`` over the canonical mesh.

The reference framework scales *batch only* (SURVEY.md §2.6); its model zoo
is "whatever TF/Torch model you wrap". This module is the TPU-native
counterpart of that contract at modern scale: the training step compiles to
a single XLA program whose collectives (psum over tp, ppermute rings over
sp and pp, all_to_all over ep, psum over dp for gradients) all ride ICI.

Layout conventions (local = per-device shapes):
  tokens          [B/dp, S/sp]
  embedding       [V/tp, M]          (vocab-sharded, tied softmax)
  attention       heads sharded tp → q/k/v [B', S', H/tp, Dh], ring over sp
  mlp             w1 [M, F/tp], w2 [F/tp, M], psum(tp) after w2
  MoE             experts sharded ep; tokens dispatched via all_to_all
  layers          stacked [pp, L/pp, ...]; GPipe schedule over pp
Gradient sync: params are replicated over (dp, sp) → psum over those axes
after ``jax.grad``; tp/ep/pp-sharded leaves keep local (sharded) grads.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu._compat import axis_size, shard_map

from horovod_tpu.models.scan_util import multi_step
from horovod_tpu.parallel.ring_attention import ring_attention_spmd
from horovod_tpu.parallel.moe import moe_layer_spmd, top_k_gating


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 2048
    n_experts: int = 0          # 0 → dense FFN; >0 → MoE every layer
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    n_microbatches: int = 1     # pipeline microbatches (per pp>1)
    remat: bool = True          # jax.checkpoint each block (HBM for FLOPs)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# Parameter init (host-side, then device_put with shardings)
# ---------------------------------------------------------------------------

def init_params(rng: np.random.RandomState, cfg: TransformerConfig,
                n_stages: int = 1) -> Dict:
    """Initialize parameters in the stacked-stage layout ``[pp, L/pp, ...]``."""
    L = cfg.n_layers
    assert L % n_stages == 0, (L, n_stages)
    lps = L // n_stages
    M, H, Dh, F = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff

    def w(*shape, scale=None):
        scale = scale if scale is not None else (1.0 / np.sqrt(shape[-2]))
        return (rng.randn(*shape) * scale).astype(np.float32)

    layer: Dict[str, np.ndarray] = {
        "ln1": np.ones((n_stages, lps, M), np.float32),
        "wq": w(n_stages, lps, M, H * Dh),
        "wk": w(n_stages, lps, M, H * Dh),
        "wv": w(n_stages, lps, M, H * Dh),
        "wo": w(n_stages, lps, H * Dh, M),
        "ln2": np.ones((n_stages, lps, M), np.float32),
    }
    if cfg.n_experts > 0:
        layer.update({
            "router": w(n_stages, lps, M, cfg.n_experts, scale=0.02),
            "we1": w(n_stages, lps, cfg.n_experts, M, F),
            "we2": w(n_stages, lps, cfg.n_experts, F, M),
        })
    else:
        layer.update({
            "w1": w(n_stages, lps, M, F),
            "w2": w(n_stages, lps, F, M),
        })
    return {
        "embed": (rng.randn(cfg.vocab_size, M) * 0.02).astype(np.float32),
        "ln_f": np.ones((M,), np.float32),
        "layers": layer,
    }


def param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Dict:
    """NamedSharding tree matching :func:`init_params` layout."""
    def s(*spec):
        return NamedSharding(mesh, P(*spec))
    tp = "tp" if mesh.shape.get("tp", 1) > 1 else None
    pp = "pp" if mesh.shape.get("pp", 1) > 1 else None
    ep = "ep" if mesh.shape.get("ep", 1) > 1 else None
    layers = {
        "ln1": s(pp), "ln2": s(pp),
        "wq": s(pp, None, None, tp), "wk": s(pp, None, None, tp),
        "wv": s(pp, None, None, tp), "wo": s(pp, None, tp, None),
    }
    if cfg.n_experts > 0:
        layers.update({
            "router": s(pp),
            "we1": s(pp, None, ep, None, tp),
            "we2": s(pp, None, ep, tp, None),
        })
    else:
        layers.update({"w1": s(pp, None, None, tp),
                       "w2": s(pp, None, tp, None)})
    return {"embed": s(tp), "ln_f": s(), "layers": layers}


def shard_params(params: Dict, cfg: TransformerConfig, mesh: Mesh) -> Dict:
    sh = param_shardings(cfg, mesh)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(jnp.asarray(x), s), params, sh)


# ---------------------------------------------------------------------------
# SPMD building blocks (all run inside shard_map over the full mesh)
# ---------------------------------------------------------------------------

def _axis_live(name: str) -> bool:
    """True if ``name`` is a manual axis of size > 1 in the current context."""
    try:
        return axis_size(name) > 1
    except NameError:
        return False


def _psum_if(x, name):
    return lax.psum(x, name) if _axis_live(name) else x


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6)
            ).astype(x.dtype) * g.astype(x.dtype)


def _rope(x, positions):
    """Rotary embedding; x [B, S, H, D], positions [S] absolute."""
    B, S, H, D = x.shape
    half = D // 2
    freqs = 1.0 / (10000 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[None, :, None, :].astype(x.dtype)
    sin = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _embed_lookup(emb_local, tokens):
    """Vocab-sharded embedding lookup: mask + psum over tp."""
    Vl, M = emb_local.shape
    if _axis_live("tp"):
        off = lax.axis_index("tp") * Vl
        idx = tokens - off
        ok = (idx >= 0) & (idx < Vl)
        x = jnp.where(ok[..., None],
                      emb_local[jnp.clip(idx, 0, Vl - 1)], 0)
        return lax.psum(x, "tp")
    return emb_local[tokens]


def _sharded_softmax_xent(logits_local, targets):
    """Cross-entropy with vocab dim sharded over tp. logits [B, S, V/tp]."""
    lf = logits_local.astype(jnp.float32)
    m_loc = jnp.max(lf, axis=-1)
    # stability shift only — stop the gradient *before* pmax (pmax has no
    # differentiation rule, and the shift cancels in exact arithmetic)
    m_loc = lax.stop_gradient(m_loc)
    m = lax.pmax(m_loc, "tp") if _axis_live("tp") else m_loc
    se = jnp.sum(jnp.exp(lf - m[..., None]), axis=-1)
    se = _psum_if(se, "tp")
    Vl = lf.shape[-1]
    if _axis_live("tp"):
        off = lax.axis_index("tp") * Vl
        idx = targets - off
        ok = (idx >= 0) & (idx < Vl)
        corr = jnp.take_along_axis(
            lf, jnp.clip(idx, 0, Vl - 1)[..., None], axis=-1)[..., 0]
        corr = lax.psum(jnp.where(ok, corr, 0.0), "tp")
    else:
        corr = jnp.take_along_axis(lf, targets[..., None], axis=-1)[..., 0]
    return jnp.log(se) + m - corr     # [B, S]


def _softmax_xent(logits_local, targets):
    """Dispatch: tp-sharded vocab takes the psum algebra above; a full
    local vocab takes the fused Pallas kernel (one HBM pass over the
    logits; auto-falls back off-TPU / untiled — same self-gating pattern
    as ``pallas_attention.attend``)."""
    if _axis_live("tp"):
        return _sharded_softmax_xent(logits_local, targets)
    from horovod_tpu.ops.pallas_xent import fused_softmax_xent
    return fused_softmax_xent(logits_local, targets)


def _attention_block(p, x, positions, cfg: TransformerConfig):
    """x: [B', S', M] local. Heads sharded over tp; sequence over sp."""
    B, S, M = x.shape
    h = _rmsnorm(x, p["ln1"])
    q = (h @ p["wq"].astype(h.dtype))
    k = (h @ p["wk"].astype(h.dtype))
    v = (h @ p["wv"].astype(h.dtype))
    Hl = q.shape[-1] // cfg.head_dim
    q = q.reshape(B, S, Hl, cfg.head_dim)
    k = k.reshape(B, S, Hl, cfg.head_dim)
    v = v.reshape(B, S, Hl, cfg.head_dim)
    q, k = _rope(q, positions), _rope(k, positions)
    if _axis_live("sp"):
        o = ring_attention_spmd(q, k, v, "sp", causal=True)
    else:
        # pallas flash kernel on TPU when tiling permits, XLA otherwise
        from horovod_tpu.ops.pallas_attention import attend
        o = attend(q, k, v, causal=True)
    o = o.reshape(B, S, Hl * cfg.head_dim) @ p["wo"].astype(x.dtype)
    o = _psum_if(o, "tp")
    return x + o


def _dense_ffn(p, x):
    h = jax.nn.gelu(x @ p["w1"].astype(x.dtype))
    o = h @ p["w2"].astype(x.dtype)
    return _psum_if(o, "tp")


def _moe_ffn(p, x, cfg: TransformerConfig):
    """x: [B', S', M] local → tokens [G, M]; experts over ep, inner mats tp."""
    B, S, M = x.shape
    toks = x.reshape(B * S, M)

    def expert_fn(ep_params, t):
        h = jax.nn.gelu(t @ ep_params["w1"].astype(t.dtype))
        o = h @ ep_params["w2"].astype(t.dtype)
        return _psum_if(o, "tp")

    y, metrics = moe_layer_spmd(
        toks, p["router"].astype(jnp.float32),
        expert_fn, {"w1": p["we1"], "w2": p["we2"]},
        axis_name="ep" if _axis_live("ep") else None,
        k=cfg.moe_top_k, capacity_factor=cfg.capacity_factor)
    return y.reshape(B, S, M), metrics


def _block(p, x, positions, cfg: TransformerConfig):
    x = _attention_block(p, x, positions, cfg)
    h = _rmsnorm(x, p["ln2"])
    if cfg.n_experts > 0:
        o, metrics = _moe_ffn(p, h, cfg)
        aux = metrics.aux_loss
    else:
        o, aux = _dense_ffn(p, h), jnp.zeros((), jnp.float32)
    return x + o.astype(x.dtype), aux


def _stage_fn_factory(cfg: TransformerConfig, positions):
    """Returns stage_fn(stage_params, act) running L/pp blocks via scan.

    The MoE aux loss rides as one extra feature column of the activation so
    the pipeline carry stays a single array (pipeline_spmd requirement); it
    accumulates across stages and is read back after the pipeline.
    """
    def one_block(x, lp):
        def fn(xx):
            return _block(lp, xx, positions, cfg)
        if cfg.remat:
            fn = jax.checkpoint(fn)
        return fn(x)

    def stage_fn(stage_params, act_with_aux):
        act = act_with_aux[..., :-1]
        aux_in = act_with_aux[..., -1:]
        def scan_body(x, lp):
            y, aux = one_block(x, lp)
            return y, aux
        y, auxs = lax.scan(scan_body, act.astype(cfg.dtype), stage_params)
        aux_out = aux_in + jnp.sum(auxs) / max(cfg.n_layers, 1)
        return jnp.concatenate([y.astype(jnp.float32), aux_out], axis=-1)

    return stage_fn


# ---------------------------------------------------------------------------
# Forward + loss (SPMD body)
# ---------------------------------------------------------------------------

def forward_loss_spmd(params, tokens, targets, cfg: TransformerConfig):
    """Local shapes: tokens/targets [B', S']. Returns (loss, aux_loss)."""
    B, S = tokens.shape
    sp_idx = lax.axis_index("sp") if _axis_live("sp") else 0
    positions = sp_idx * S + jnp.arange(S)

    x = _embed_lookup(params["embed"].astype(cfg.dtype), tokens)  # [B,S,M]

    lp = params["layers"]
    n_stages = lp["ln1"].shape[0]
    aux_total = jnp.zeros((), jnp.float32)

    if _axis_live("pp"):
        from horovod_tpu.parallel.pipeline import (pipeline_spmd,
                                                   psum_cotangent)
        stage_fn = _stage_fn_factory(cfg, positions)
        aux_col = jnp.zeros(x.shape[:-1] + (1,), jnp.float32)
        xa = jnp.concatenate([x.astype(jnp.float32), aux_col], -1)
        # the embedding is computed replicated over pp, but only stage 0
        # CONSUMES its output — without this, the lookup's gradient
        # contribution exists only on the pp-rank-0 shards and the
        # assembled embed gradient depends on which replica the
        # out_specs pick (pipeline.py module docstring)
        xa = psum_cotangent(xa, "pp")
        M = cfg.n_microbatches
        xm = xa.reshape((M, B // M) + xa.shape[1:])
        ym = pipeline_spmd(stage_fn, lp, xm, "pp")
        ya = ym.reshape((B,) + ym.shape[2:])
        x = ya[..., :-1].astype(cfg.dtype)
        aux_total = jnp.mean(ya[..., -1])
    else:
        # no pipeline: scan all layers of the single stage
        def scan_body(carry, layer_p):
            y, aux = _block(layer_p, carry, positions, cfg)
            return y, aux
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), lp)
        x, auxs = lax.scan(scan_body, x, flat)
        aux_total = jnp.sum(auxs) / max(cfg.n_layers, 1)

    x = _rmsnorm(x, params["ln_f"])
    logits_local = x @ params["embed"].astype(cfg.dtype).T    # [B,S,V/tp]
    nll = _softmax_xent(logits_local, targets)                # [B,S]
    loss = jnp.mean(nll)
    # average over data-like axes so every shard reports the global loss
    # (ep subdivides the batch — see data_sharding_spec)
    for ax in ("dp", "ep", "sp"):
        if _axis_live(ax):
            loss = lax.pmean(loss, ax)
            aux_total = lax.pmean(aux_total, ax)
    return loss, aux_total


# ---------------------------------------------------------------------------
# Jitted train/eval step factories
# ---------------------------------------------------------------------------

def data_sharding_spec(mesh: Mesh) -> P:
    """Batch dim shards over every live data-like axis (dp and — because
    expert parallelism subdivides the data-parallel groups, DeepSpeed-MoE
    style — ep); sequence dim over sp."""
    batch_axes = tuple(a for a in ("dp", "ep") if mesh.shape.get(a, 1) > 1)
    sp = "sp" if mesh.shape.get("sp", 1) > 1 else None
    return P(batch_axes if batch_axes else None, sp)


def _grad_sync(grads, pspec):
    """psum each gradient over the *data* axes (dp, ep, sp) its parameter is
    replicated over; axes present in the leaf's own sharding spec (tp/ep on
    sharded weights, pp on stages) keep shard-local gradients — the Megatron
    rule, and the in-graph analog of the reference's allreduce hooks
    (``torch/optimizer.py:164-206``)."""
    def one(g, spec):
        used = set()
        for part in spec:
            if part is None:
                continue
            if isinstance(part, (tuple, list)):
                used.update(part)
            else:
                used.add(part)
        for ax in ("dp", "ep", "sp"):
            if ax not in used:
                g = _psum_if(g, ax)
        return g
    return jax.tree_util.tree_map(one, grads, pspec)


def make_grad_fn(cfg: TransformerConfig, mesh: Mesh):
    """SPMD (loss, aux, grads) function over the mesh; grads come back with
    param shardings, ready for any optax optimizer applied under jit."""
    data_spec = data_sharding_spec(mesh)
    psh = param_shardings(cfg, mesh)
    pspec = jax.tree_util.tree_map(lambda s: s.spec, psh)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(pspec, data_spec, data_spec),
        out_specs=(P(), P(), pspec),
        check_vma=False)
    def grad_fn(params, tokens, targets):
        def loss_fn(p):
            loss, aux = forward_loss_spmd(p, tokens, targets, cfg)
            return loss + 0.01 * aux, (loss, aux)
        grads, (loss, aux) = jax.grad(loss_fn, has_aux=True)(params)
        grads = _grad_sync(grads, pspec)
        return loss, aux, grads

    return grad_fn


def make_train_step(cfg: TransformerConfig, mesh: Mesh, optimizer,
                    scan_steps: int = 1):
    """Jitted full train step: manual-SPMD fwd/bwd (shard_map) + optimizer
    update in GSPMD-auto mode (XLA keeps the elementwise update sharded as
    the params are).

    ``scan_steps > 1`` runs that many optimizer steps per call via
    ``lax.scan`` in ONE compiled program (one dispatch per chain; see
    ``make_resnet_train_step``). All scanned steps consume the SAME
    ``tokens``/``targets`` batch (``scan_util.multi_step`` same-batch
    semantics — a throughput construct, not multi-batch training).
    Returned loss/aux are the last step's.

    ``params``/``opt_state`` buffers are DONATED (in-place update on
    device): keep only the returned state — the inputs are invalidated
    after the call on TPU."""
    import optax
    grad_fn = make_grad_fn(cfg, mesh)

    def one_step(params, opt_state, tokens, targets):
        loss, aux, grads = grad_fn(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, aux

    chain = multi_step(one_step, n_carry=2, scan_steps=scan_steps)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, targets):
        return chain(params, opt_state, tokens, targets)

    return step


def make_forward(cfg: TransformerConfig, mesh: Mesh):
    """Jitted forward (loss only) — used by ``__graft_entry__.entry``."""
    data_spec = data_sharding_spec(mesh)
    psh = param_shardings(cfg, mesh)
    pspec = jax.tree_util.tree_map(lambda s: s.spec, psh)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(pspec, data_spec, data_spec),
                       out_specs=P(), check_vma=False)
    def fwd(params, tokens, targets):
        loss, aux = forward_loss_spmd(params, tokens, targets, cfg)
        return loss + 0.01 * aux

    return jax.jit(fwd)


def init_opt_state(optimizer, params, mesh: Mesh, cfg: TransformerConfig):
    """Initialize optimizer state under jit so every state leaf inherits the
    corresponding parameter's sharding (adam moments mirror params; scalars
    replicate)."""
    return jax.jit(optimizer.init)(params)


def shard_batch(tokens, targets, mesh: Mesh):
    spec = data_sharding_spec(mesh)
    sh = NamedSharding(mesh, spec)
    return jax.device_put(tokens, sh), jax.device_put(targets, sh)


# ---------------------------------------------------------------------------
# Generative decode: KV-cache forward over the serving engine's paged pool
# ---------------------------------------------------------------------------
# The serving-side decode path (horovod_tpu/serving/generate/) runs the
# SAME weights the training step produced, but at token granularity: one
# fixed-shape decode step over a static slot array, with K/V history in
# block-granular pages.  Everything below is single-device math in fp32
# (serving replicas are world_size=1; bitwise-stable greedy decode is
# the parity contract tests/test_generate.py enforces).  Layout:
#
#   k_pages / v_pages  [L, total_pages + 1, page_tokens, H*Dh]
#       (+1 = the scratch page inactive/padded lanes write into, so
#       membership churn never changes the compiled shape)
#   page_table         [slots, pages_per_slot] int32 — a slot's j-th
#       page holds its token positions [j*page_tokens, (j+1)*page_tokens);
#       gathered back, position p of a slot lands at flat index p.

def kv_cache_spec(cfg: TransformerConfig) -> Tuple[int, int, Any]:
    """(n_layers, per-token K width, cache dtype) — the model
    fingerprint the page planner sizes pages from."""
    return cfg.n_layers, cfg.n_heads * cfg.head_dim, jnp.float32


def flatten_decode_params(params: Dict) -> Dict:
    """Collapse the stacked-stage layout ``[pp, L/pp, ...]`` to
    ``[L, ...]`` — decode scans all layers on one device; the pipeline
    split is a training-time concern."""
    layers = params["layers"]
    if "w1" not in layers:
        raise NotImplementedError(
            "paged decode supports dense-FFN transformers (n_experts=0)")
    flat = {k: jnp.asarray(v).reshape((-1,) + tuple(np.shape(v)[2:]))
            for k, v in layers.items()}
    return {"embed": jnp.asarray(params["embed"]),
            "ln_f": jnp.asarray(params["ln_f"]),
            "layers": flat}


def _rope_rows(x, pos):
    """Rotary embedding for per-row positions: x [N, H, D], pos [N] —
    the decode-time counterpart of :func:`_rope` (one token per row,
    each at its own absolute position)."""
    half = x.shape[-1] // 2
    freqs = 1.0 / (10000 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]   # [N, half]
    cos = jnp.cos(ang)[:, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _paged_layer(lp, x, q_pos, kv_pages, dest_page, offs, gather_rows,
                 key_mask, cfg: TransformerConfig):
    """One transformer block over paged KV: write this call's K/V into
    the pool, gather the full history back, attend, FFN.

    x [N, M] (N = slots for decode, chunk for prefill); ``dest_page``/
    ``offs`` [N] address each row's write; ``gather_rows`` indexes the
    pages to read back ([N, P] per-row for decode, [P] shared for
    prefill); ``key_mask`` [N, T] marks the attended positions."""
    kp, vp = kv_pages
    H, Dh = cfg.n_heads, cfg.head_dim
    N = x.shape[0]
    h = _rmsnorm(x, lp["ln1"].astype(jnp.float32))
    q = _rope_rows((h @ lp["wq"].astype(jnp.float32)).reshape(N, H, Dh),
                   q_pos)
    k = _rope_rows((h @ lp["wk"].astype(jnp.float32)).reshape(N, H, Dh),
                   q_pos)
    v = (h @ lp["wv"].astype(jnp.float32))
    kp = kp.at[dest_page, offs].set(k.reshape(N, H * Dh))
    vp = vp.at[dest_page, offs].set(v)
    k_all = kp[gather_rows].reshape(gather_rows.shape[:-1] + (-1, H, Dh))
    v_all = vp[gather_rows].reshape(gather_rows.shape[:-1] + (-1, H, Dh))
    if k_all.ndim == 3:           # shared gather (prefill): [T, H, Dh]
        scores = jnp.einsum("nhd,thd->nht", q, k_all)
    else:                         # per-row gather (decode): [N, T, H, Dh]
        scores = jnp.einsum("nhd,nthd->nht", q, k_all)
    scores = scores / np.sqrt(Dh).astype(np.float32)
    scores = jnp.where(key_mask[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if k_all.ndim == 3:
        o = jnp.einsum("nht,thd->nhd", probs, v_all)
    else:
        o = jnp.einsum("nht,nthd->nhd", probs, v_all)
    x = x + o.reshape(N, H * Dh) @ lp["wo"].astype(jnp.float32)
    h2 = _rmsnorm(x, lp["ln2"].astype(jnp.float32))
    f = jax.nn.gelu(h2 @ lp["w1"].astype(jnp.float32))
    return x + f @ lp["w2"].astype(jnp.float32), (kp, vp)


def decode_step_paged(params: Dict, k_pages, v_pages, page_table,
                      lengths, last_token, active,
                      cfg: TransformerConfig):
    """ONE decode step for every slot at once — the function the engine
    jits exactly once, whatever joins or leaves between calls.

    Shapes (all static): page_table [S, P] int32, lengths/last_token
    [S] int32, active [S] bool.  Each active slot embeds its last
    token, appends its K/V at position ``lengths[s]``, attends over its
    own gathered history, and emits the greedy next token.  Inactive
    slots compute masked garbage into the scratch page — their lanes
    exist only to keep the shape constant.  Returns
    ``(next_token [S] int32, k_pages, v_pages)``."""
    S = last_token.shape[0]
    pt = k_pages.shape[2]
    scratch = k_pages.shape[1] - 1
    emb = params["embed"].astype(jnp.float32)
    x = emb[last_token]                                    # [S, M]
    page_idx = jnp.clip(lengths // pt, 0, page_table.shape[1] - 1)
    dest = jnp.take_along_axis(page_table, page_idx[:, None], axis=1)[:, 0]
    dest = jnp.where(active, dest, scratch)
    offs = lengths % pt
    T = page_table.shape[1] * pt
    key_mask = jnp.arange(T)[None, :] <= lengths[:, None]  # incl. new token

    def body(x, layer):
        lp, kp, vp = layer
        x, pages = _paged_layer(lp, x, lengths, (kp, vp), dest, offs,
                                page_table, key_mask, cfg)
        return x, pages

    x, (k_pages, v_pages) = lax.scan(
        body, x, (params["layers"], k_pages, v_pages))
    x = _rmsnorm(x, params["ln_f"].astype(jnp.float32))
    logits = x @ emb.T                                     # [S, V]
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), k_pages, v_pages


def prefill_chunk_paged(params: Dict, k_pages, v_pages, page_row,
                        tokens, pos0, valid, cfg: TransformerConfig):
    """Prefill ONE ``chunk``-token slice of ONE slot's prompt (fixed
    chunk shape — the last chunk arrives padded with ``valid`` marking
    the real tokens).  Writes the chunk's K/V into the slot's pages and
    returns the greedy next token after the last VALID position — the
    first generated token once the final chunk lands.  Returns
    ``(next_token scalar int32, k_pages, v_pages)``."""
    C = tokens.shape[0]
    pt = k_pages.shape[2]
    scratch = k_pages.shape[1] - 1
    emb = params["embed"].astype(jnp.float32)
    x = emb[tokens]                                        # [C, M]
    pos = pos0 + jnp.arange(C, dtype=jnp.int32)
    live = jnp.arange(C) < valid
    dest = jnp.where(live,
                     page_row[jnp.clip(pos // pt, 0,
                                       page_row.shape[0] - 1)],
                     scratch)
    offs = pos % pt
    T = page_row.shape[0] * pt
    # causal within the chunk AND over every earlier chunk's positions
    key_mask = jnp.arange(T)[None, :] <= pos[:, None]

    def body(x, layer):
        lp, kp, vp = layer
        x, pages = _paged_layer(lp, x, pos, (kp, vp), dest, offs,
                                page_row, key_mask, cfg)
        return x, pages

    x, (k_pages, v_pages) = lax.scan(
        body, x, (params["layers"], k_pages, v_pages))
    x = _rmsnorm(x, params["ln_f"].astype(jnp.float32))
    x_last = x[jnp.clip(valid - 1, 0, C - 1)]
    logits = x_last @ emb.T                                # [V]
    return jnp.argmax(logits).astype(jnp.int32), k_pages, v_pages


def reference_greedy_decode(params: Dict, cfg: TransformerConfig,
                            prompt, max_new: int) -> list:
    """Sequential non-paged oracle: recompute full-history attention
    for every emitted token (no cache, no paging, no batching).  Slow
    on purpose — this is the ground truth the paged continuous engine
    must match token-for-token (tests/test_generate.py)."""
    flat = flatten_decode_params(params)
    H, Dh, L = cfg.n_heads, cfg.head_dim, cfg.n_layers
    toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
    out = []
    for _ in range(int(max_new)):
        ids = jnp.asarray(toks, dtype=jnp.int32)
        Tn = ids.shape[0]
        emb = flat["embed"].astype(jnp.float32)
        x = emb[ids]
        pos = jnp.arange(Tn, dtype=jnp.int32)
        for li in range(L):
            lp = {k: v[li] for k, v in flat["layers"].items()}
            h = _rmsnorm(x, lp["ln1"].astype(jnp.float32))
            q = _rope_rows((h @ lp["wq"].astype(jnp.float32))
                           .reshape(Tn, H, Dh), pos)
            k = _rope_rows((h @ lp["wk"].astype(jnp.float32))
                           .reshape(Tn, H, Dh), pos)
            v = (h @ lp["wv"].astype(jnp.float32)).reshape(Tn, H, Dh)
            scores = jnp.einsum("qhd,khd->hqk", q, k) / np.sqrt(Dh)
            mask = pos[None, :] <= pos[:, None]
            scores = jnp.where(mask[None, :, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("hqk,khd->qhd", probs, v).reshape(Tn, H * Dh)
            x = x + o @ lp["wo"].astype(jnp.float32)
            h2 = _rmsnorm(x, lp["ln2"].astype(jnp.float32))
            f = jax.nn.gelu(h2 @ lp["w1"].astype(jnp.float32))
            x = x + f @ lp["w2"].astype(jnp.float32)
        x = _rmsnorm(x, flat["ln_f"].astype(jnp.float32))
        nxt = int(jnp.argmax(x[-1] @ emb.T))
        out.append(nxt)
        toks.append(nxt)
    return out
