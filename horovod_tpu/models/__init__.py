from horovod_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    init_params,
    param_shardings,
    shard_params,
    make_train_step,
    make_grad_fn,
    make_forward,
    init_opt_state,
    shard_batch,
    data_sharding_spec,
)
