from horovod_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    init_params,
    param_shardings,
    shard_params,
    make_train_step,
    make_grad_fn,
    make_forward,
    init_opt_state,
    shard_batch,
    data_sharding_spec,
)

# CNN zoo (the reference's published benchmark models) + BERT are imported
# lazily by path — `horovod_tpu.models.{resnet,vgg,inception,bert}` — to
# keep `import horovod_tpu` light. Every entry is a "module:constructor"
# returning a model/config object when called with no arguments; resolve
# with `get_model(name)`. (The flagship dp/pp/tp/sp/ep transformer is
# config-driven — see `TransformerConfig` above — and not in this index.)
MODEL_ZOO = {
    "resnet50": "horovod_tpu.models.resnet:ResNet50",
    "resnet101": "horovod_tpu.models.resnet:ResNet101",
    "vgg16": "horovod_tpu.models.vgg:VGG16",
    "inception3": "horovod_tpu.models.inception:InceptionV3",
    "bert_large": "horovod_tpu.models.bert:bert_large",
    "bert_base": "horovod_tpu.models.bert:bert_base",
}


def get_model(name: str, **kwargs):
    """Resolve a MODEL_ZOO entry to its constructed model/config."""
    import importlib
    try:
        module, attr = MODEL_ZOO[name].split(":")
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: "
                       f"{sorted(MODEL_ZOO)}") from None
    return getattr(importlib.import_module(module), attr)(**kwargs)
