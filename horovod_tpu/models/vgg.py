"""VGG-16 — the third model in the reference's published benchmark table
(reference: ``docs/benchmarks.rst:13-14`` — 68% scaling efficiency at 512
GPUs; its lower efficiency comes from the huge FC layers' gradient volume,
which makes it the stress case for gradient-allreduce bandwidth).

TPU-native: flax in bf16, NHWC, data-parallel GSPMD-auto like the ResNet
family. The 4096-wide FC matmuls land squarely on the MXU, so on TPU this
model is compute-friendly; it remains the gradient-bandwidth stress test
(~138M params → ~276 MB of bf16 gradients per step vs ResNet-50's ~51 MB).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from horovod_tpu.models.scan_util import multi_step
import flax.linen as nn

# (convs per stage, channels) — the classic "D" configuration
VGG16_STAGES: Sequence = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))


class VGG(nn.Module):
    stages: Sequence = VGG16_STAGES
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, kernel_size=(3, 3),
                                 padding="SAME", dtype=self.dtype)
        for n_convs, ch in self.stages:
            for _ in range(n_convs):
                x = nn.relu(conv(ch)(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        for _ in range(2):
            x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def VGG16(num_classes: int = 1000, dtype=jnp.bfloat16) -> VGG:
    return VGG(VGG16_STAGES, num_classes, dtype)


def create_vgg_state(model: VGG, rng_key, image_size: int = 224,
                     mesh=None):
    """Init params, replicated over the mesh (no batch stats: VGG has no
    BN in the classic configuration)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    variables = model.init({"params": rng_key},
                           jnp.zeros((1, image_size, image_size, 3),
                                     model.dtype), train=False)
    params = variables["params"]
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        params = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rep), params)
    return params


def make_vgg_train_step(model: VGG, optimizer, mesh, dropout_seed: int = 0,
                        scan_steps: int = 1):
    """Data-parallel train step; same GSPMD-auto contract as the ResNet
    step (``make_resnet_train_step``). ``step_idx`` is folded into the
    dropout key so every step draws a fresh mask (callers must pass an
    incrementing value; it is a traced scalar, so varying it does not
    recompile).

    ``scan_steps > 1`` runs that many optimizer steps per call via
    ``lax.scan`` in ONE compiled program (one dispatch per chain; see
    ``make_resnet_train_step``); scanned step ``i`` uses dropout index
    ``step_idx * scan_steps + i`` so masks stay fresh. All scanned steps
    consume the SAME batch (``scan_util.multi_step`` same-batch
    semantics — a throughput construct, not multi-batch training).

    ``params``/``opt_state`` buffers are DONATED (in-place update on
    device): keep only the returned state — the inputs are invalidated
    after the call on TPU."""
    import optax

    def one_step(params, opt_state, images, labels, step_idx):
        def loss_fn(p):
            key = jax.random.fold_in(
                jax.random.PRNGKey(dropout_seed), step_idx)
            logits = model.apply({"params": p}, images, train=True,
                                 rngs={"dropout": key})
            one_hot = jax.nn.one_hot(labels, logits.shape[-1])
            return optax.softmax_cross_entropy(logits, one_hot).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    chain = multi_step(one_step, n_carry=2, scan_steps=scan_steps,
                       indexed=True)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, images, labels, step_idx=0):
        return chain(params, opt_state, images, labels, step_idx)

    return step
