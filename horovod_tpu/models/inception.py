"""Inception V3 — completes the reference's published benchmark table
(reference: ``docs/benchmarks.rst:13-14`` — Inception V3 at 90% scaling
efficiency on 512 GPUs, alongside ResNet-101 and VGG-16).

TPU-native: flax in bf16 with fp32 BN statistics, NHWC, GSPMD-auto data
parallel like the rest of the model zoo. The factorized 1x7/7x1 convs are
exactly the shapes XLA tiles well on the MXU. The auxiliary classifier
head is omitted: it exists for optimization of the original 2015 training
recipe, contributes nothing to throughput benchmarking, and modern
recipes drop it.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from horovod_tpu.models.scan_util import multi_step
import flax.linen as nn


class ConvBN(nn.Module):
    """conv + BN + relu, the Inception building block."""
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b5 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train),
                           train)
        bp = c(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b3 = c(384, (3, 3), (2, 2), "VALID")(x, train)
        bd = c(96, (3, 3), (2, 2), "VALID")(
            c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionB(nn.Module):
    """17x17 blocks with factorized 7x7 convolutions."""
    c7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(192, (1, 1))(x, train)
        b7 = c(192, (7, 1))(c(self.c7, (1, 7))(
            c(self.c7, (1, 1))(x, train), train), train)
        bd = c(192, (1, 7))(c(self.c7, (7, 1))(c(self.c7, (1, 7))(
            c(self.c7, (7, 1))(c(self.c7, (1, 1))(x, train), train),
            train), train), train)
        bp = c(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b3 = c(320, (3, 3), (2, 2), "VALID")(c(192, (1, 1))(x, train),
                                             train)
        b7 = c(192, (3, 3), (2, 2), "VALID")(
            c(192, (7, 1))(c(192, (1, 7))(c(192, (1, 1))(x, train), train),
                           train), train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionC(nn.Module):
    """8x8 blocks with split 1x3/3x1 branches."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        s = c(384, (1, 1))(x, train)
        b3 = jnp.concatenate([c(384, (1, 3))(s, train),
                              c(384, (3, 1))(s, train)], axis=-1)
        d = c(384, (3, 3))(c(448, (1, 1))(x, train), train)
        bd = jnp.concatenate([c(384, (1, 3))(d, train),
                              c(384, (3, 1))(d, train)], axis=-1)
        bp = c(192, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        # stem: 299x299x3 -> 35x35x192
        x = c(32, (3, 3), (2, 2), "VALID")(x, train)
        x = c(32, (3, 3), (1, 1), "VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1), (1, 1), "VALID")(x, train)
        x = c(192, (3, 3), (1, 1), "VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 35x35
        for pf in (32, 64, 64):
            x = InceptionA(pf, self.dtype)(x, train)
        x = ReductionA(self.dtype)(x, train)
        # 17x17
        for c7 in (128, 160, 160, 192):
            x = InceptionB(c7, self.dtype)(x, train)
        x = ReductionB(self.dtype)(x, train)
        # 8x8
        for _ in range(2):
            x = InceptionC(self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def create_inception_state(model: InceptionV3, rng_key,
                           image_size: int = 299, mesh=None):
    from jax.sharding import NamedSharding, PartitionSpec as P
    variables = model.init(
        {"params": rng_key},
        jnp.zeros((1, image_size, image_size, 3), model.dtype),
        train=False)
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        variables = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rep), variables)
    return variables["params"], variables["batch_stats"]


def make_inception_train_step(model: InceptionV3, optimizer, mesh,
                              dropout_seed: int = 0, scan_steps: int = 1):
    """``step_idx`` is folded into the dropout key so every step draws a
    fresh mask (callers must pass an incrementing value; it is a traced
    scalar, so varying it does not recompile).

    ``scan_steps > 1`` runs that many optimizer steps per call via
    ``lax.scan`` in ONE compiled program (one dispatch per chain; see
    ``make_resnet_train_step``); scanned step ``i`` uses dropout index
    ``step_idx * scan_steps + i`` so masks stay fresh. All scanned steps
    consume the SAME batch (``scan_util.multi_step`` same-batch
    semantics — a throughput construct, not multi-batch training).

    ``params``/``batch_stats``/``opt_state`` buffers are DONATED
    (in-place update on device): keep only the returned state — the
    inputs are invalidated after the call on TPU."""
    import optax

    def one_step(params, batch_stats, opt_state, images, labels, step_idx):
        def loss_fn(p):
            key = jax.random.fold_in(
                jax.random.PRNGKey(dropout_seed), step_idx)
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"],
                rngs={"dropout": key})
            one_hot = jax.nn.one_hot(labels, logits.shape[-1])
            loss = optax.softmax_cross_entropy(logits, one_hot).mean()
            return loss, mut["batch_stats"]
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    chain = multi_step(one_step, n_carry=3, scan_steps=scan_steps,
                       indexed=True)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, batch_stats, opt_state, images, labels, step_idx=0):
        return chain(params, batch_stats, opt_state, images, labels,
                     step_idx)

    return step
