"""ResNet-50 (v1.5) — the reference's headline benchmark model family
(reference: ``examples/pytorch/pytorch_imagenet_resnet50.py``,
``docs/benchmarks.rst``: ResNet-class CNNs at 90% scaling efficiency).

TPU-native: flax module in bf16 with fp32 BN statistics, trained
data-parallel in GSPMD-auto mode — batch sharded over ``dp``, params
replicated; XLA inserts the gradient all-reduce the reference does with
NCCL ring-allreduce (``nccl_operations.cc:156-214``). NHWC layout (TPU
conv-friendly); matmul-heavy bottlenecks land on the MXU.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.models.scan_util import multi_step


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        needs_proj = x.shape[-1] != self.filters * 4 or self.strides != (1, 1)
        residual = x
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = functools.partial(nn.BatchNorm, use_running_average=not train,
                               momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        y = conv(self.filters, (1, 1))(x)
        y = bn()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), self.strides)(y)
        y = bn()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if needs_proj:
            residual = conv(self.filters * 4, (1, 1), self.strides)(residual)
            residual = bn()(residual)
        return nn.relu(y + residual)


def space_to_depth(x: jax.Array, block: int = 2) -> jax.Array:
    """[B, H, W, C] -> [B, H/b, W/b, C*b*b] (pixel-shuffle inverse)."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // block, block, W // block, block, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        B, H // block, W // block, C * block * block)


class ResNet(nn.Module):
    """``stem="conv"`` is the textbook 7x7/s2 stem. ``stem="s2d"`` is the
    MLPerf-TPU space-to-depth stem: the 7x7/s2 conv over C=3 tiles the MXU
    terribly (3 input channels against a 128-wide systolic array);
    space-to-depth(2) turns it into a 4x4/s1 conv over 12 channels with
    the same receptive field and output shape, cutting the stem's padding
    waste 4x.
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    stem: str = "conv"
    remat: bool = False  # jax.checkpoint each block: HBM for recompute,
    #                      unlocking larger per-chip batches (PERF.md (b))
    remat_prevent_cse: bool = True  # pass False when the step runs inside
    #                      lax.scan (scan_steps>1): flax documents the CSE
    #                      barrier as unnecessary there, and it costs

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.stem == "s2d":
            x = space_to_depth(x, 2)  # [B, 112, 112, 12]
            x = nn.Conv(64, (4, 4), (1, 1), padding="SAME",
                        use_bias=False, dtype=self.dtype)(x)
        elif self.stem == "conv":
            x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype)(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r}; expected 'conv' or 's2d'")
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        # static_argnums counts (self, x, train): train must be passed
        # POSITIONALLY for the lifted remat to see it as static. The
        # explicit name pins the param path to the PLAIN class's
        # auto-name, so init RNG streams and checkpoints are identical
        # whether remat is on or off.
        block_cls = nn.remat(
            BottleneckBlock, static_argnums=(2,),
            prevent_cse=self.remat_prevent_cse) \
            if self.remat else BottleneckBlock
        block_idx = 0
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(64 * 2 ** i, strides, self.dtype,
                              name=f"BottleneckBlock_{block_idx}")(x, train)
                block_idx += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def ResNet50(num_classes: int = 1000, dtype=jnp.bfloat16,
             stem: str = "conv", remat: bool = False,
             remat_prevent_cse: bool = True) -> ResNet:
    return ResNet([3, 4, 6, 3], num_classes, dtype, stem, remat,
                  remat_prevent_cse)


def ResNet101(num_classes: int = 1000, dtype=jnp.bfloat16,
              stem: str = "conv", remat: bool = False,
              remat_prevent_cse: bool = True) -> ResNet:
    return ResNet([3, 4, 23, 3], num_classes, dtype, stem, remat,
                  remat_prevent_cse)


def create_resnet_state(model: ResNet, rng_key, image_size: int = 224,
                        mesh: Mesh = None):
    """Init params/batch_stats, replicated over the mesh."""
    variables = model.init(rng_key, jnp.zeros((1, image_size, image_size, 3),
                                              model.dtype), train=True)
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        variables = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rep), variables)
    return variables["params"], variables["batch_stats"]


def make_resnet_train_step(model: ResNet, optimizer, mesh: Mesh,
                           scan_steps: int = 1):
    """Data-parallel train step (GSPMD-auto): batch sharded over every
    data-like axis; gradient reduction inserted by XLA from shardings —
    functionally identical to the reference's DistributedOptimizer loop
    (``torch/optimizer.py:314-325``) with fusion/overlap done by the
    compiler instead of the background thread.

    ``scan_steps > 1`` runs that many optimizer steps per call via
    ``lax.scan`` inside ONE compiled program: a single dispatch covers
    the whole chain, taking host→device launch latency (significant
    through a remote relay) off the critical path. Every scanned step
    consumes the SAME ``images``/``labels`` batch (the scan carries only
    the training state — ``scan_util.multi_step``): right for
    throughput measurement, NOT a substitute for multi-batch training —
    feed a fresh batch per call with ``scan_steps=1`` for real epochs.
    The returned loss is the LAST scanned step's.

    ``params``/``batch_stats``/``opt_state`` buffers are DONATED: the
    update happens in place on device, so keep only the returned state
    (the inputs are invalidated after the call on TPU)."""

    def one_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(labels, logits.shape[-1])
            loss = optax.softmax_cross_entropy(logits, one_hot).mean()
            return loss, mut["batch_stats"]
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    chain = multi_step(one_step, n_carry=3, scan_steps=scan_steps)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, batch_stats, opt_state, images, labels):
        return chain(params, batch_stats, opt_state, images, labels)

    return step


def batch_sharding(mesh: Mesh) -> NamedSharding:
    axes = tuple(a for a in ("dp", "ep", "sp", "pp", "tp")
                 if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(axes if axes else None))
