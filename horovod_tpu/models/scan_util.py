"""In-graph multi-step chaining shared by the model train-step factories.

``lax.scan`` of K optimizer steps inside one compiled program: a single
dispatch covers the whole chain, taking host→device launch latency
(significant through a remote TPU relay) off the critical path. Factories
wrap the returned chain in their own ``jax.jit`` so each keeps its public
signature (incl. keyword ``step_idx``) and donation contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def multi_step(one_step, n_carry: int, scan_steps: int,
               indexed: bool = False):
    """Chain ``one_step`` into ``scan_steps`` sequential optimizer steps.

    ``one_step(*carry, *consts[, step_idx]) -> (*carry, *outs)`` where the
    first ``n_carry`` positional args (and results) are the training state
    threaded through the chain and the rest are loop-invariant inputs.
    Returns a function of the same positional signature yielding the final
    carry plus the LAST step's outs.

    ``indexed=True`` treats the final argument as a step index: scanned
    step ``i`` receives ``step_idx * scan_steps + i``, so per-step dropout
    keys stay fresh across both the chain and successive dispatches.

    SAME-BATCH semantics: the non-carry inputs (the batch) are
    loop-invariant — every scanned step consumes the SAME batch, so
    ``scan_steps > 1`` means K optimizer steps on one batch per
    dispatch. That is the right construct for throughput benchmarking
    (device-rate measurement with dispatch latency off the critical
    path) and deliberate multi-epoch-per-batch training; it is NOT
    multi-batch training — a training loop that wants a fresh batch per
    optimizer step must keep ``scan_steps=1`` (or restructure the batch
    as a scanned ``[K, ...]`` input itself).

    ``scan_steps <= 1`` returns ``one_step`` behavior unchanged (guarding
    0/negative values: a zero-length scan would run no steps at all).
    """
    if scan_steps <= 1:
        return one_step

    def chained(*args):
        carry0 = args[:n_carry]
        consts = args[n_carry:]
        if indexed:
            *consts, step_idx = consts

        def body(carry, i):
            if indexed:
                res = one_step(*carry, *consts,
                               step_idx * scan_steps + i)
            else:
                res = one_step(*carry, *consts)
            return res[:n_carry], res[n_carry:]

        carry, outs = jax.lax.scan(
            body, carry0,
            jnp.arange(scan_steps) if indexed else None,
            length=None if indexed else scan_steps)
        return (*carry, *jax.tree_util.tree_map(lambda x: x[-1], outs))

    return chained
