"""Worker entry for agent-transport elastic jobs (Spark/Ray): fetch the
pickled training fn from the driver KV, run it, publish this rank's
result (reference analog: ``spark/task/__init__.py`` exec of the pickled
fn in the task process)."""

from __future__ import annotations

import os
import sys


def main() -> int:
    import cloudpickle
    from horovod_tpu.runner.elastic.agent import resolve_kv_addr
    from horovod_tpu.runner.http_kv import kv_get, kv_put

    addr, port = os.environ["HVD_AGENT_KV"].rsplit(":", 1)
    addr = resolve_kv_addr(addr)
    payload = kv_get(addr, int(port), "payload", "fn")
    if payload is None:
        print("agent_worker: no payload published", file=sys.stderr)
        return 1
    fn, args, kwargs = cloudpickle.loads(payload)
    result = fn(*args, **kwargs)
    # generation-scoped key: a late publish from an aborted generation
    # must never be mistaken for (or overwrite) the completed one's
    gen = os.environ.get("HVD_ELASTIC_GENERATION", "0")
    kv_put(addr, int(port), "result",
           f"{gen}.{os.environ['HOROVOD_RANK']}",
           cloudpickle.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
