"""Elastic host discovery.

Reference: ``horovod/runner/elastic/discovery.py`` — ``HostDiscoveryScript``
(user script printing ``host:slots`` lines), ``FixedHosts``, and
``HostManager`` tracking diffs + blacklist. On TPU the script typically
enumerates pod-slice hosts (e.g. from the cloud metadata service) instead of
GPU nodes.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List, Optional, Set

from horovod_tpu.runner.hosts import HostInfo


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; stdout lines ``hostname:slots`` (reference:
    ``HostDiscoveryScript.find_available_hosts_and_slots``). Lines
    without an explicit ``:slots`` get ``default_slots`` (the launcher's
    ``--slots-per-host``)."""

    def __init__(self, script_path: str, default_slots: int = 1) -> None:
        self._script = script_path
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run([self._script], capture_output=True,
                             timeout=60, check=True, shell=False)
        hosts: Dict[str, int] = {}
        for line in out.stdout.decode().splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                hosts[host] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    def __init__(self, hosts: List[HostInfo]) -> None:
        self._hosts = {h.hostname: h.slots for h in hosts}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks current/blacklisted hosts and computes ordered assignments
    with rank stability (reference: ``HostManager`` + the driver's
    stable-rank assignment, ``elastic/driver.py:233-275``)."""

    def __init__(self, discovery: HostDiscovery) -> None:
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current: Dict[str, int] = {}
        self._blacklist: Set[str] = set()
        self._order: List[str] = []   # stable ordering of known hosts

    def blacklist(self, host: str) -> None:
        with self._lock:
            self._blacklist.add(host)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._blacklist

    def update_available_hosts(self) -> bool:
        """Refresh; True if the usable host set changed (reference:
        discovery thread, ``driver.py:181-201``)."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            usable = {h: s for h, s in found.items()
                      if h not in self._blacklist}
            changed = usable != self._current
            self._current = usable
            # stable order: keep existing positions, append new hosts
            self._order = [h for h in self._order if h in usable] + \
                [h for h in usable if h not in self._order]
            return changed

    def current_hosts(self) -> List[HostInfo]:
        with self._lock:
            return [HostInfo(h, self._current[h]) for h in self._order]

    def slot_count(self) -> int:
        with self._lock:
            return sum(self._current.values())
