"""Elastic host discovery.

Reference: ``horovod/runner/elastic/discovery.py`` — ``HostDiscoveryScript``
(user script printing ``host:slots`` lines), ``FixedHosts``, and
``HostManager`` tracking diffs + blacklist. On TPU the script typically
enumerates pod-slice hosts (e.g. from the cloud metadata service) instead of
GPU nodes.
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.runner.hosts import HostInfo


def blocklist_cooldown_s() -> float:
    """``HVD_TPU_BLOCKLIST_COOLDOWN_S``: how long a blocklisted host
    stays excluded before it is retried (maintenance ends, the host
    comes back — a permanent blocklist turns every transient host event
    into permanently lost capacity).  0 = never re-admit (the
    pre-cooldown behavior)."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("BLOCKLIST_COOLDOWN_S", 600.0))


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; stdout lines ``hostname:slots`` (reference:
    ``HostDiscoveryScript.find_available_hosts_and_slots``). Lines
    without an explicit ``:slots`` get ``default_slots`` (the launcher's
    ``--slots-per-host``)."""

    def __init__(self, script_path: str, default_slots: int = 1) -> None:
        self._script = script_path
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run([self._script], capture_output=True,
                             timeout=60, check=True, shell=False)
        hosts: Dict[str, int] = {}
        for line in out.stdout.decode().splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                hosts[host] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    def __init__(self, hosts: List[HostInfo]) -> None:
        self._hosts = {h.hostname: h.slots for h in hosts}

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks current/blacklisted/draining hosts and computes ordered
    assignments with rank stability (reference: ``HostManager`` + the
    driver's stable-rank assignment, ``elastic/driver.py:233-275``).

    Two time-bounded exclusion mechanisms ride the discovery refresh:

    * **blocklist cooldown** — a blocklisted host is re-admitted (and
      retried) once ``HVD_TPU_BLOCKLIST_COOLDOWN_S`` has passed; a host
      that was merely under maintenance is capacity again, and a host
      that is genuinely bad earns its way straight back onto the list.
    * **drain reservations** — a preemption drain reserves N slots on
      the doomed host for a cooldown window, so replacement placement
      cannot land workers back on a host that announced its own death;
      expiry re-admits the capacity (→ the growth path re-spawns).
    """

    def __init__(self, discovery: HostDiscovery) -> None:
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current: Dict[str, int] = {}
        self._blacklist: Dict[str, float] = {}   # host -> listed-at
        self._block_evidence: Dict[str, dict] = {}  # host -> why
        self._drained: Dict[str, tuple] = {}     # host -> (slots, expiry)
        self._order: List[str] = []   # stable ordering of known hosts

    def blacklist(self, host: str, evidence: Optional[dict] = None) -> None:
        """``evidence`` is the decision record — what convinced the
        driver this host is bad (failure counts, quarantine finding...).
        It rides into the control-plane journal so a takeover driver can
        show WHY a host is excluded, not just that it is."""
        with self._lock:
            self._blacklist[host] = time.monotonic()
            if evidence is not None:
                self._block_evidence[host] = dict(evidence)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._blacklist

    def drain(self, host: str, slots: int, cooldown_s: float) -> None:
        """Reserve ``slots`` on ``host`` for ``cooldown_s`` (stacking
        onto any live reservation, capped later against the host's real
        capacity) and apply it to the CURRENT view immediately — the
        drain re-mesh places replacements in the same loop iteration,
        before the discovery thread's next refresh."""
        with self._lock:
            prev_slots, prev_expiry = self._drained.get(host, (0, 0.0))
            now = time.monotonic()
            live = prev_slots if prev_expiry > now else 0
            self._drained[host] = (live + max(0, slots),
                                   now + max(0.0, cooldown_s))
            if host in self._current:
                self._current[host] = max(
                    0, self._current[host] - max(0, slots))

    def undrain(self, host: str, slots: int) -> None:
        """Release ``slots`` of a drain reservation (the driver found no
        viable planned world and is falling back to reactive recovery —
        the doomed host must stay usable until it actually dies)."""
        with self._lock:
            prev_slots, expiry = self._drained.get(host, (0, 0.0))
            left = max(0, prev_slots - max(0, slots))
            if left:
                self._drained[host] = (left, expiry)
            else:
                self._drained.pop(host, None)
            if host in self._current:
                self._current[host] += max(0, slots)

    def _usable(self, found: Dict[str, int]) -> Dict[str, int]:
        """Apply blocklist (with cooldown re-admission) and unexpired
        drain reservations to a discovery result.  Caller holds _lock."""
        now = time.monotonic()
        cooldown = blocklist_cooldown_s()
        for host in [h for h, at in self._blacklist.items()
                     if cooldown > 0 and now - at >= cooldown]:
            del self._blacklist[host]
            self._block_evidence.pop(host, None)
            try:
                from horovod_tpu.common.logging import get_logger
                get_logger().info(
                    "blocklist cooldown expired: re-admitting host %s",
                    host)
            except Exception:
                pass
        for host in [h for h, (_s, exp) in self._drained.items()
                     if exp <= now]:
            del self._drained[host]
        usable = {}
        for h, s in found.items():
            if h in self._blacklist:
                continue
            drained_slots = self._drained.get(h, (0, 0.0))[0]
            usable[h] = max(0, s - drained_slots)
        return usable

    def update_available_hosts(self) -> bool:
        """Refresh; True if the usable host set changed (reference:
        discovery thread, ``driver.py:181-201``)."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            usable = self._usable(found)
            changed = usable != self._current
            self._current = usable
            # stable order: keep existing positions, append new hosts
            self._order = [h for h in self._order if h in usable] + \
                [h for h in usable if h not in self._order]
            return changed

    def current_hosts(self) -> List[HostInfo]:
        with self._lock:
            return [HostInfo(h, self._current[h]) for h in self._order
                    if self._current[h] > 0]

    def slot_count(self) -> int:
        with self._lock:
            return sum(self._current.values())

    # -- takeover persistence -------------------------------------------------
    def dump_state(self) -> Dict[str, dict]:
        """Exclusion state as WALL-clock-stamped plain data for the
        control-plane journal.  Monotonic stamps are process-local and
        meaningless to a takeover driver, so each entry converts to the
        wall clock at dump time; :meth:`restore_state` re-ages them back
        to this semantics in the new process.  Format:
        ``{"blocklist": {host: {"ts": wall, "evidence": {...}}},
        "drains": {host: {"slots": n, "remaining_s": secs, "ts": wall}}}``
        """
        now_mono = time.monotonic()
        now_wall = time.time()
        with self._lock:
            return {
                "blocklist": {
                    h: {"ts": now_wall - (now_mono - at),
                        "evidence": self._block_evidence.get(h)}
                    for h, at in self._blacklist.items()},
                "drains": {
                    h: {"slots": slots,
                        "remaining_s": max(0.0, exp - now_mono),
                        "ts": now_wall}
                    for h, (slots, exp) in self._drained.items()},
            }

    def restore_state(self, blocklist: Dict[str, dict],
                      drains: Dict[str, dict]) -> None:
        """Re-adopt journaled exclusion state (takeover).  Wall stamps
        re-age onto this process's monotonic clock: a host blocklisted
        9 minutes before the old driver died, restored 30s later under a
        10-minute cooldown, is re-admitted in ~2.5 minutes — NOT given a
        fresh 10 minutes (the cooldown promise is to the host, not the
        process).  Drain reservations restore only their remaining
        window, aged by the wall time since the dump."""
        now_mono = time.monotonic()
        now_wall = time.time()
        with self._lock:
            for host, rec in blocklist.items():
                elapsed = max(0.0, now_wall - float(rec.get("ts",
                                                            now_wall)))
                self._blacklist[host] = now_mono - elapsed
                ev = rec.get("evidence")
                if ev is not None:
                    self._block_evidence[host] = dict(ev)
            for host, rec in drains.items():
                elapsed = max(0.0, now_wall - float(rec.get("ts",
                                                            now_wall)))
                remaining = float(rec.get("remaining_s", 0.0)) - elapsed
                if remaining <= 0:
                    continue  # the reservation expired during the outage
                slots = int(rec.get("slots", 0))
                prev_slots, prev_exp = self._drained.get(host, (0, 0.0))
                live = prev_slots if prev_exp > now_mono else 0
                self._drained[host] = (max(live, slots),
                                       now_mono + remaining)

    def block_evidence(self, host: str) -> Optional[dict]:
        with self._lock:
            ev = self._block_evidence.get(host)
            return dict(ev) if ev is not None else None
