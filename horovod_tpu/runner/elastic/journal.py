"""Control-plane journal: the elastic driver's write-ahead log.

The driver (:mod:`horovod_tpu.runner.elastic.driver`) holds the job's
entire control state in one process's memory — generation counters, the
signed world doc, blocklist/drain evidence, handled-notice dedupe.  This
module makes that state crash-durable so "driver restart is not a job
restart" (docs/ELASTIC.md "Driver failover & takeover"): every
state-changing decision is appended here, fsync'd, **before** the
corresponding KV publish.  That ordering is the whole safety argument —
the journal is always at least as new as anything the fleet has seen, so
replay can complete an interrupted publish but can never resurrect a
world the fleet already moved past.

Format: one JSON object per line, with ``"t"`` (the record type) as the
FIRST key so even a torn tail's prefix reveals what was being written.
A torn tail (partial last line — the write raced the crash) is normally
dropped; the one exception is a torn ``world_publish``: we cannot know
whether the fleet saw that world, so :meth:`ReplayState.check_takeover`
refuses takeover and points the operator at the backstop generation
restart instead.

Rotation is atomic à la the OBS/reqlog readers: the compacted journal is
written to a sibling ``.new`` file, fsync'd, then ``os.replace``d over
the live path — a reader (or a crash) sees either the old file or the
new one, never a mix, and the newest generation's records survive the
compaction verbatim.

Replay is a pure fold (:func:`replay`): record order in, state dict out,
no I/O, no clocks.  Every fold step uses set/last-wins semantics so
replaying a journal twice yields the same state as once, and an unknown
record type is skipped LOUDLY (warning + counter) — a newer driver's
journal must degrade, not explode, under an older one's replay.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from horovod_tpu.common.config import env_int, env_str
from horovod_tpu.common.logging import get_logger

JOURNAL_NAME = "driver_journal.jsonl"

#: record types this reader understands (order matters nowhere; the set
#: exists so replay can tell "unknown" from "known" explicitly)
RECORD_TYPES = frozenset({
    "job_open",        # identity: secret, kv port, ckpt dir, np bounds
    "world_publish",   # full signed doc + the post-publish gen runtime
    "spawn",           # worker process started: (gen, rank) -> host/pid
    "exit",            # worker exit classified: (gen, rank) -> state
    "blocklist",       # host blocklisted, with evidence + wall stamp
    "drain",           # host drained (slots, cooldown, wall stamp)
    "undrain",         # drain lifted early
    "token",           # drain-notice/action token handled (dedupe)
    "notify",          # worker listener registration observed: rank -> addr
    "reset",           # registry reset budget: absolute count
    "takeover",        # a takeover driver adopted this journal
    "clean_exit",      # the driver returned normally (rc) — not a crash
})


def journal_dir() -> Optional[str]:
    """``HVD_TPU_DRIVER_JOURNAL_DIR``: where the driver journals; unset
    (the default) disables journaling and takeover entirely."""
    return env_str("DRIVER_JOURNAL_DIR") or None


def journal_max_bytes() -> int:
    """``HVD_TPU_DRIVER_JOURNAL_MAX_BYTES`` (default 4 MiB): compaction
    threshold, checked at world-publish boundaries."""
    return env_int("DRIVER_JOURNAL_MAX_BYTES", 4 * 1024 * 1024)


class TakeoverRefused(RuntimeError):
    """The journal cannot prove what the fleet saw; takeover would risk
    publishing a stale world.  The safe exit is the existing backstop:
    restart the generation (workers re-rendezvous from the last elastic
    checkpoint — docs/ELASTIC.md "Generation-restart backstop")."""


def _dumps(rtype: str, fields: Dict[str, Any]) -> str:
    # "t" first, by construction: dicts preserve insertion order and
    # json.dumps emits in that order unless sort_keys is set
    rec = {"t": rtype}
    rec.update(fields)
    return json.dumps(rec, default=_json_default)


def _json_default(o):
    if isinstance(o, (set, frozenset)):
        return sorted(o)
    if isinstance(o, bytes):
        return o.hex()
    return str(o)


def _key(k) -> list:
    """(gen, rank) tuples JSON-ify as lists; keep them that way on the
    wire and convert back at fold time."""
    return list(k)


def _untuple(k) -> tuple:
    return tuple(k)


def _metrics_update(path: str, records: int) -> None:
    try:
        from horovod_tpu.metrics.registry import default_registry
        reg = default_registry()
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        reg.gauge("hvd_driver_journal_bytes",
                  help="size of the driver control-plane journal").set(
                      size)
        reg.gauge("hvd_driver_journal_records",
                  help="records appended to the driver journal this "
                       "incarnation").set(records)
    except Exception:
        pass


class DriverJournal:
    """Append-only, fsync'd writer.  One instance per driver
    incarnation; a takeover driver opens the SAME path in append mode
    and keeps writing — the journal spans incarnations by design."""

    def __init__(self, directory: str, name: str = JOURNAL_NAME) -> None:
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, name)
        self._lock = threading.Lock()
        self._records = 0
        self._fh: Optional[io.TextIOWrapper] = open(
            self.path, "a", encoding="utf-8")

    # -- writing ---------------------------------------------------------
    def append(self, rtype: str, **fields) -> None:
        """Durably append one record: write, flush, fsync.  Raises on
        I/O failure — a driver that cannot journal must not keep making
        decisions it cannot replay."""
        line = _dumps(rtype, fields)
        with self._lock:
            fh = self._fh
            if fh is None:
                raise RuntimeError("journal is closed")
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())
            self._records += 1
        _metrics_update(self.path, self._records)

    def maybe_compact(self, max_bytes: Optional[int] = None) -> bool:
        """At a world-publish boundary: if the file outgrew the
        threshold, rewrite it as the minimal record set that replays to
        the same state (atomic ``.new`` + ``os.replace``).  Returns
        whether a compaction happened."""
        limit = journal_max_bytes() if max_bytes is None else max_bytes
        with self._lock:
            try:
                if os.path.getsize(self.path) <= limit:
                    return False
            except OSError:
                return False
            records, torn = read_journal(self.path)
            state = replay(records, torn)
            new_path = self.path + ".new"
            with open(new_path, "w", encoding="utf-8") as out:
                for rec in state.canonical_records():
                    out.write(json.dumps(rec, default=_json_default)
                              + "\n")
                out.flush()
                os.fsync(out.fileno())
            # close-then-replace: the live handle must not keep
            # appending to the orphaned inode
            if self._fh is not None:
                self._fh.close()
            os.replace(new_path, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
        get_logger().info("driver journal compacted (%s)", self.path)
        _metrics_update(self.path, self._records)
        return True

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
                self._fh.close()
                self._fh = None


# -- reading -----------------------------------------------------------------
def read_journal(path: str) -> Tuple[List[dict], Optional[str]]:
    """Parse the journal into ``(records, torn_tail)``.

    ``torn_tail`` is the raw prefix of a partial last line (no trailing
    newline — the append raced a crash), or None when the file ends
    cleanly.  A complete mid-file line that fails to parse is skipped
    loudly: corruption, not a torn write, and dropping one record is
    recoverable where refusing the whole journal is not."""
    log = get_logger()
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return [], None
    torn: Optional[str] = None
    chunks = raw.split(b"\n")
    if chunks and chunks[-1] != b"":
        torn = chunks[-1].decode("utf-8", errors="replace")
        chunks = chunks[:-1]
    records: List[dict] = []
    for i, chunk in enumerate(chunks):
        if not chunk:
            continue
        try:
            rec = json.loads(chunk)
            if not isinstance(rec, dict) or "t" not in rec:
                raise ValueError("not a journal record")
        except ValueError as e:
            log.warning("driver journal %s line %d unreadable (%r); "
                        "skipping", path, i + 1, e)
            continue
        records.append(rec)
    if torn is not None:
        log.warning("driver journal %s has a torn tail (%d bytes, "
                    "prefix %r)", path, len(torn), torn[:48])
    return records, torn


def torn_tail_type(torn: Optional[str]) -> Optional[str]:
    """Best-effort record type of a torn tail, from the type-first key
    ordering the writer guarantees."""
    if not torn:
        return None
    for rtype in RECORD_TYPES:
        if torn.startswith('{"t": "%s"' % rtype) or \
                torn.startswith('{"t":"%s"' % rtype):
            return rtype
    return None


class ReplayState:
    """The fold result: everything a takeover driver needs to become
    the driver.  Pure data — restoring it into live objects is the
    driver's job (:meth:`ElasticDriver.takeover_from_journal`)."""

    def __init__(self) -> None:
        self.meta: Dict[str, Any] = {}        # last job_open
        self.world: Optional[dict] = None     # last world_publish
        self.live: Dict[tuple, dict] = {}     # (gen, rank) -> spawn rec
        self.exits: Dict[tuple, dict] = {}    # (gen, rank) -> exit rec
        self.blocklist: Dict[str, dict] = {}  # host -> evidence rec
        self.drains: Dict[str, dict] = {}     # host -> drain rec
        self.tokens: set = set()              # (scope, key, raw)
        self.notify: Dict[str, dict] = {}     # rank -> notify rec
        self.reset_count = 0
        self.takeovers: set = set()           # (pid, ts) markers
        self.clean_exit: Optional[int] = None
        self.unknown = 0
        self.torn_tail: Optional[str] = None

    # -- the fold --------------------------------------------------------
    def fold(self, rec: dict) -> None:
        t = rec.get("t")
        if t == "job_open":
            self.meta = dict(rec)
            # a new job_open supersedes everything before it: same
            # journal path reused for a fresh job
            self.world = None
            self.live.clear()
            self.exits.clear()
            self.blocklist.clear()
            self.drains.clear()
            self.tokens.clear()
            self.notify.clear()
            self.reset_count = 0
            self.clean_exit = None
        elif t == "world_publish":
            self.world = dict(rec)
            # listener registrations are per numbering window: the
            # driver clears the ``notify`` scope at every publish and
            # workers re-register at their first commit in the new
            # world, so replay forgets them the same way
            self.notify.clear()
            self.clean_exit = None
        elif t == "spawn":
            key = _untuple(rec["key"])
            self.live[key] = dict(rec)
            self.exits.pop(key, None)
        elif t == "exit":
            key = _untuple(rec["key"])
            self.exits[key] = dict(rec)
            self.live.pop(key, None)
        elif t == "blocklist":
            self.blocklist[rec["host"]] = dict(rec)
        elif t == "drain":
            self.drains[rec["host"]] = dict(rec)
        elif t == "undrain":
            self.drains.pop(rec.get("host"), None)
        elif t == "token":
            self.tokens.add((rec["scope"], rec["key"],
                             rec.get("raw", "")))
        elif t == "notify":
            self.notify[str(rec["rank"])] = dict(rec)
        elif t == "reset":
            self.reset_count = int(rec.get("count", 0))
        elif t == "takeover":
            self.takeovers.add((rec.get("pid"), rec.get("ts")))
        elif t == "clean_exit":
            self.clean_exit = int(rec.get("rc", 0))
        else:
            self.unknown += 1
            get_logger().warning(
                "driver journal: unknown record type %r skipped "
                "(fields: %s) — written by a newer driver?", t,
                sorted(rec.keys()))
            try:
                from horovod_tpu.metrics.registry import \
                    default_registry
                default_registry().counter(
                    "hvd_driver_journal_unknown_total",
                    help="journal records skipped on replay because "
                         "their type is unknown").inc()
            except Exception:
                pass

    # -- queries ---------------------------------------------------------
    @property
    def world_gen(self) -> int:
        return int((self.world or {}).get("world_gen", 0))

    @property
    def numbering_gen(self) -> int:
        return int((self.world or {}).get("numbering_gen", 0))

    def live_workers(self) -> Dict[tuple, dict]:
        """Spawned-but-not-exited workers of the LAST published world's
        numbering window — the set the takeover driver must adopt."""
        lo, hi = self.numbering_gen, self.world_gen
        return {k: v for k, v in self.live.items()
                if lo <= k[0] <= hi}

    def check_takeover(self) -> None:
        """Raise :class:`TakeoverRefused` when replay cannot produce a
        world the fleet provably saw."""
        tail_type = torn_tail_type(self.torn_tail)
        if tail_type == "world_publish":
            raise TakeoverRefused(
                "journal ends in a half-written world_publish: the KV "
                "publish may or may not have reached the fleet, so a "
                "replayed world could be one generation stale. Refusing "
                "takeover — restart the job and let the generation-"
                "restart backstop re-rendezvous workers from the last "
                "elastic checkpoint (docs/ELASTIC.md).")
        if self.world is None:
            raise TakeoverRefused(
                "journal holds no committed world_publish record: "
                "nothing to take over. Start the job normally (the "
                "generation-restart backstop applies if workers are "
                "still running).")
        if self.clean_exit is not None:
            raise TakeoverRefused(
                "journal ends in clean_exit rc=%d: the previous driver "
                "finished on purpose; there is nothing to take over."
                % self.clean_exit)

    def canonical_records(self) -> List[dict]:
        """Minimal record list that folds back to this state — the
        compaction payload.  The newest world's records are re-emitted
        verbatim so the live generation's history survives rotation."""
        out: List[dict] = []
        if self.meta:
            out.append(self.meta)
        for host in sorted(self.blocklist):
            out.append(self.blocklist[host])
        for host in sorted(self.drains):
            out.append(self.drains[host])
        for scope, key, raw in sorted(self.tokens):
            out.append({"t": "token", "scope": scope, "key": key,
                        "raw": raw})
        out.append({"t": "reset", "count": self.reset_count})
        for pid, ts in sorted(self.takeovers,
                              key=lambda p: (p[1] or 0, p[0] or 0)):
            out.append({"t": "takeover", "pid": pid, "ts": ts})
        if self.world is not None:
            out.append(self.world)
        # after the world record: fold() forgets registrations at every
        # world_publish, so emitting them first would lose them
        for rank in sorted(self.notify):
            out.append(self.notify[rank])
        for key in sorted(self.exits):
            rec = self.exits[key]
            if self.world is not None and \
                    key[0] < self.numbering_gen:
                continue  # pre-window history: replay would ignore it
            out.append(rec)
        for key in sorted(self.live):
            out.append(self.live[key])
        if self.clean_exit is not None:
            out.append({"t": "clean_exit", "rc": self.clean_exit})
        return out


def replay(records: List[dict],
           torn: Optional[str] = None) -> ReplayState:
    """Pure fold: records in, :class:`ReplayState` out.  Feeding the
    same journal twice (or the concatenation of a journal with itself)
    yields the same state — every fold step is last-wins or set-add."""
    state = ReplayState()
    for rec in records:
        state.fold(rec)
    state.torn_tail = torn
    try:
        from horovod_tpu.metrics.registry import default_registry
        default_registry().counter(
            "hvd_driver_journal_replayed_total",
            help="journal records folded during takeover replay").inc(
                len(records))
    except Exception:
        pass
    return state


def load(path: str) -> ReplayState:
    """read + replay in one step (what ``--takeover`` calls)."""
    records, torn = read_journal(path)
    return replay(records, torn)


def now_wall() -> float:
    """Wall time for journal stamps.  Monotonic stamps are meaningless
    across processes, so records carry wall time and restore re-ages:
    ``remaining = cooldown - (now_wall - stamp_wall)``."""
    return time.time()
