"""Agent-transport elastic execution, shared by the Spark and Ray
integrations.

Reference: the task-service exec model of ``horovod.spark.run_elastic``
(``spark/runner.py:309-430``) and ``horovod.ray.ElasticRayExecutor``
(``ray/elastic.py:149+``) — the cluster framework owns process placement,
so the elastic driver cannot ssh; instead every framework task/actor runs
a HOST AGENT loop that registers a heartbeat in a driver-side KV,
executes HMAC-signed worker commands the ElasticDriver routes to it, and
reports exit codes. Agent loss → heartbeat expiry → shrink; the
framework's retry respawns the agent → grow.

Trust model: command docs are integrity-protected (HMAC over a secret
shipped through the framework's own serialization channel, never the KV),
and secrets — including the elastic world-doc key — stay off the wire;
the KV itself, like the reference's rendezvous server, assumes the
cluster-private network. Do not expose the KV port outside it.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import subprocess
import sys
import time
import uuid as uuidlib
from typing import Any, Callable, Dict, List, Optional

HEARTBEAT_S = 1.0
STALE_S = 10.0

_ENV_SHIP_PREFIXES = ("HOROVOD_", "HVD_", "PATH", "PYTHONPATH")


def _sign(secret: bytes, body: bytes) -> str:
    return hmac.new(secret, body, hashlib.sha256).hexdigest()


def resolve_kv_addr(addr: str) -> str:
    """Same-box fast path: a process on the driver's own host talks to the
    KV over loopback (the advertised name may not resolve from inside
    containers, and loopback skips the NIC)."""
    import socket
    if socket.gethostname() == addr.split(".")[0]:
        return "127.0.0.1"
    return addr


# -- agent side (runs inside a Spark task / Ray actor) ----------------------

def agent_loop(ordinal: int, kv_addr: str, kv_port: int,
               secret_hex: str, world_secret_hex: str = "") -> None:
    """Register as a host agent and execute signed worker commands until
    the driver posts shutdown (reference analog: the task service loop,
    ``runner/common/service/task_service.py``).

    The world-doc secret arrives through the framework's serialization
    channel (this function's arguments), NOT over the KV — the agent
    injects it into each worker's environment locally."""
    import collections
    import socket
    from horovod_tpu.runner.http_kv import kv_get, kv_put

    secret = bytes.fromhex(secret_hex)
    host = socket.gethostname()
    agent_id = f"{host}@{ordinal}"  # '@' is URL-path-safe; '#' would be
    # stripped as a URI fragment by the HTTP KV client
    seen = collections.OrderedDict()  # bounded processed-uuid memory
    proc: Optional[subprocess.Popen] = None
    cur_uuid: Optional[str] = None

    def beat() -> None:
        kv_put(kv_addr, kv_port, "agents", agent_id, json.dumps(
            {"host": host, "ts": time.time()}).encode())

    beat()
    last_beat = time.time()
    while True:
        now = time.time()
        if now - last_beat >= HEARTBEAT_S:
            beat()
            last_beat = now
        if kv_get(kv_addr, kv_port, "ctl", "shutdown") is not None:
            if proc is not None and proc.poll() is None:
                proc.terminate()
            return
        if proc is not None:
            if kv_get(kv_addr, kv_port, "kill", cur_uuid) is not None \
                    and proc.poll() is None:
                proc.terminate()
            rc = proc.poll()
            if rc is not None:
                kv_put(kv_addr, kv_port, "rc", cur_uuid,
                       str(rc).encode())
                proc, cur_uuid = None, None
        else:
            doc = kv_get(kv_addr, kv_port, "cmd", agent_id)
            if doc:
                body, _, sig = doc.rpartition(b"|")
                if sig and hmac.compare_digest(sig.decode(),
                                               _sign(secret, body)):
                    spec = json.loads(body)
                    if spec["uuid"] not in seen:
                        seen[spec["uuid"]] = True
                        while len(seen) > 64:
                            seen.popitem(last=False)
                        cur_uuid = spec["uuid"]
                        wenv = {**os.environ, **spec["env"]}
                        if world_secret_hex:
                            wenv["HVD_ELASTIC_SECRET"] = world_secret_hex
                        proc = subprocess.Popen(spec["cmd"], env=wenv)
        time.sleep(0.25)


# -- driver side ------------------------------------------------------------

class AgentRegistryDiscovery:
    """Host discovery over the agent registry: one slot per agent whose
    heartbeat is fresh (reference analog: the driver's view of registered
    task services)."""

    def __init__(self, kv) -> None:
        self._kv = kv

    def agents_on(self, host: str) -> List[str]:
        out = []
        for agent_id, blob in sorted(self._kv.scope("agents").items()):
            meta = json.loads(blob)
            if meta["host"] == host and \
                    time.time() - meta["ts"] < STALE_S:
                out.append(agent_id)
        return out

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        slots: Dict[str, int] = {}
        for agent_id, blob in self._kv.scope("agents").items():
            meta = json.loads(blob)
            if time.time() - meta["ts"] < STALE_S:
                slots[meta["host"]] = slots.get(meta["host"], 0) + 1
        return slots


def make_agent_exec(kv, discovery: AgentRegistryDiscovery, secret: bytes,
                    user_env_keys=()):
    """remote_exec for ElasticDriver: route (command, env) to the agent
    occupying this slot and wait for its exit code.

    Only launcher-owned env keys (and the caller's explicit ``env``
    overrides) travel in the command doc — the agent merges them over ITS
    task environment, so driver-side credentials never cross the network
    (the ssh launcher filters exports the same way, ``exec_run.py
    slot_command``)."""

    def _exec(slot, command: List[str], wenv: Dict[str, str],
              events) -> int:
        agents = discovery.agents_on(slot.hostname)
        if len(agents) <= slot.local_rank:
            # an agent's heartbeat went stale between assignment and
            # launch; failing the slot restarts the generation cleanly
            # rather than doubling two slots onto one agent
            return 1
        agent_id = agents[slot.local_rank]
        uid = uuidlib.uuid4().hex
        ship = {k: v for k, v in wenv.items()
                if isinstance(v, str) and
                (k.startswith(_ENV_SHIP_PREFIXES) or k in user_env_keys)}
        body = json.dumps(
            {"uuid": uid, "cmd": list(command), "env": ship}).encode()
        kv.put("cmd", agent_id, body + b"|" + _sign(secret, body).encode())
        killed = False
        kill_deadline = None
        while True:
            rc = kv.get("rc", uid)
            if rc is not None:
                # retire the doc so the KV doesn't accumulate a full env
                # copy per launch over a long elastic job
                kv.put("cmd", agent_id, b"")
                return int(rc)
            if not killed and any(e.is_set() for e in events):
                kv.put("kill", uid, b"1")
                killed = True
                kill_deadline = time.time() + 3 * STALE_S
            # a dead agent never posts rc: give up once its heartbeat is
            # stale (task/actor loss) or a kill went unacknowledged
            if agent_id not in discovery.agents_on(slot.hostname) or \
                    (kill_deadline and time.time() > kill_deadline):
                # ALSO retire the doc: a framework-respawned agent with
                # the same id (fresh empty `seen`) must not exec this
                # dead generation's command
                kv.put("cmd", agent_id, b"")
                return 1
            time.sleep(0.1)

    return _exec


def run_agent_elastic(start_agents: Callable[[dict], Callable[[], None]],
                      fn: Callable, args: tuple = (),
                      kwargs: Optional[dict] = None,
                      num_proc: int = 1,
                      min_np: Optional[int] = None,
                      max_np: Optional[int] = None,
                      env: Optional[dict] = None,
                      reset_limit: Optional[int] = None,
                      verbose: int = 0) -> List[Any]:
    """Full agent-elastic orchestration: start the KV, ship the payload,
    let ``start_agents(ctx)`` spawn the framework-owned agents (it
    returns a cleanup callable invoked after shutdown is posted), run the
    ElasticDriver over the agent registry, and return the per-rank
    results of the generation that completed."""
    import cloudpickle
    import secrets as _secrets
    import socket as _socket
    from horovod_tpu.runner.http_kv import KVStoreServer
    from horovod_tpu.runner.elastic.driver import ElasticDriver

    kwargs = kwargs or {}
    min_np = min_np or num_proc
    max_np = max_np or num_proc

    kv = KVStoreServer()
    kv.start()
    cleanup = None
    try:
        secret = _secrets.token_bytes(16)
        world_secret = _secrets.token_bytes(16)
        kv.put("payload", "fn", cloudpickle.dumps((fn, args, kwargs)))
        # advertise the hostname, not getfqdn(): agents on other hosts
        # resolve it via cluster DNS (the reference's task-address model)
        # and same-host agents shortcut to loopback; getfqdn() can be
        # 'localhost', which resolves to ::1 while the KV server is
        # IPv4-only
        kv_addr = _socket.gethostname()
        # ctx["kv"] is the IN-PROCESS server handle for driver-side
        # helpers (e.g. the Ray respawner); framework closures must
        # capture the scalar entries, never ctx itself
        ctx = {"kv_addr": kv_addr, "kv_port": kv.port, "kv": kv,
               "secret_hex": secret.hex(),
               "world_secret_hex": world_secret.hex(), "max_np": max_np}
        cleanup = start_agents(ctx)

        discovery = AgentRegistryDiscovery(kv)
        worker_env = dict(os.environ)
        worker_env.update(env or {})
        worker_env["HVD_AGENT_KV"] = f"{kv_addr}:{kv.port}"
        driver = ElasticDriver(
            discovery,
            [sys.executable, "-u", "-m",
             "horovod_tpu.runner.elastic.agent_worker"],
            min_np=min_np, max_np=max_np, env=worker_env,
            reset_limit=reset_limit, verbose=bool(verbose),
            target_np=num_proc, world_secret=world_secret,
            remote_exec=make_agent_exec(kv, discovery, secret,
                                        user_env_keys=tuple(env or ())))
        rc = driver.run()
        if rc != 0:
            raise RuntimeError(
                f"elastic agent job failed (driver rc={rc})")
        # results are generation-scoped. Aborted generations are strictly
        # OLDER than the successful launch generation, while in-place
        # growth resyncs move a surviving worker's generation FORWARD
        # (elastic/__init__.py _apply_world_update) — so the completed
        # world's publishes are exactly those at gen >= final_generation;
        # per rank, the newest wins
        final_np = driver.final_np or 0
        final_gen = driver.final_generation or 0
        results: Dict[int, Any] = {}
        best_gen: Dict[int, int] = {}
        for key, blob in kv.scope("result").items():
            g_str, _, r_str = key.partition(".")
            g, r = int(g_str), int(r_str)
            if g >= final_gen and r < final_np and \
                    g >= best_gen.get(r, final_gen):
                best_gen[r] = g
                results[r] = cloudpickle.loads(blob)
        if sorted(results) != list(range(final_np)):
            raise RuntimeError(
                f"elastic agent job succeeded but results are missing: "
                f"have ranks {sorted(results)}, expected 0..{final_np - 1}")
        return [results[r] for r in range(final_np)]
    finally:
        kv.put("ctl", "shutdown", b"1")
        try:
            if cleanup is not None:
                cleanup()
        finally:
            kv.stop()
