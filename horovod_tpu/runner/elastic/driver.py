"""Elastic driver: discovery-driven launch/relaunch with blacklist and
rank-stable assignments.

Reference: ``horovod/runner/elastic/driver.py`` (``ElasticDriver``: discovery
thread :181-201, stable rank assignment :233-275, worker spawn per slot
:277-295, blacklist + exit handling :297-313).

TPU-native design — every world change keeps SURVIVORS in-process
(reference: the reset loop, ``common/elastic.py:151-175``); the
generation-restart path is the backstop, not the norm:

* **Crashes recover in place** (round 5): the lost worker's peers catch
  ``HorovodInternalError``, the driver publishes a recovery world and
  respawns a REPLACEMENT for the dead rank onto free discovery capacity
  (shrinking to the survivors when capacity is gone); survivors
  re-rendezvous under their (possibly renumbered) ranks with parameters
  still in host memory. Viability requires every survivor to hold a
  fresh elastic-listener registration (proof it can apply a world doc);
  recoveries share the ``--reset-limit`` budget with restarts.
* **Planned capacity loss shrinks in place**: discovery dropping slots
  publishes the kept-worker world; dropped workers exit via the
  not-in-new-world path at their next commit.
* **Growth keeps survivors running** (VERDICT r1 #6): when discovery only
  ADDS capacity, the driver publishes a new world document (generation,
  size, per-rank env, fresh rendezvous port) to its KV server and spawns
  workers for the new slots only. Survivors pick the update up at their
  next ``state.commit()`` (``HostsUpdatedInterrupt`` → in-place re-init).
  Ranks are stable under growth, so survivors keep their shard
  assignments.
* **Restart backstop**: jobs without committed elastic state, completion
  races, reshuffled assignments, or too-few survivors terminate the
  generation and relaunch from the last ``HVD_ELASTIC_CKPT`` commit
  (stable ranks, failed hosts blacklisted).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.common.logging import get_logger
from horovod_tpu.runner.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic.registration import (DRAINED, FAILURE,
                                                     SUCCESS, TERMINATED,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.exec_run import (free_port, slot_command)
from horovod_tpu.runner.hosts import HostInfo, get_host_assignments
from horovod_tpu.runner.safe_exec import safe_execute

DISCOVERY_INTERVAL_S = 1.0


def loss_settle_s() -> float:
    """``HVD_TPU_LOSS_SETTLE_S``: how long the driver lets a worker loss
    SETTLE before planning recovery.  A correlated failure (a whole host
    group dying in one chaos window, a switch losing a rack) lands as
    several process exits milliseconds apart; recovering after the first
    one would plan a world containing peers that are already dead —
    a second recovery round at best, a spurious generation restart at
    worst.  The settle window collapses the burst into ONE re-mesh."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("LOSS_SETTLE_S", 0.3))


def drain_cooldown_s() -> float:
    """``HVD_TPU_DRAIN_COOLDOWN_S``: how long a drained host's capacity
    stays reserved after its preemption notice — long enough for the
    maintenance/preemption to actually happen, short enough that a
    repaired host rejoins promptly (expiry re-admits the capacity and
    the growth path re-spawns onto it)."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("DRAIN_COOLDOWN_S", 60.0))


def restart_cooldown_s() -> float:
    """``HVD_TPU_RESTART_COOLDOWN_S``: reservation window for an
    autopilot ``restart`` action (the hbm_growth planned restart,
    docs/OBSERVABILITY.md "Autopilot").  Unlike a preemption drain the
    host is HEALTHY — the restarted worker should respawn onto it as
    soon as the old process has exited and released its chip, so the
    default is seconds, not the drain cooldown's minute."""
    from horovod_tpu.common.config import env_float
    return max(0.0, env_float("RESTART_COOLDOWN_S", 5.0))


class _GenRuntime:
    """Mutable bookkeeping of ONE running generation — the poll loop's
    former closure state, promoted to an object so the drain-notice and
    autopilot-action handlers can be driver METHODS instead of blocks
    inlined in ``_run_generation``'s poll loop (PR 10's documented
    debt, paid down as the autopilot action channel landed in the same
    loop)."""

    def __init__(self, slots, gen: int, coord_addr: str,
                 coord_port: int) -> None:
        self.failure = threading.Event()
        self.teardown = threading.Event()  # restart path: kill survivors
        self.worker_lost = threading.Event()  # crash: in-place shrink 1st
        self.fail_lock = threading.Lock()
        # per-worker bookkeeping keyed by (spawn_generation, rank): ranks
        # are reused across in-generation worlds (shrink renumbers,
        # growth appends), so the rank alone is not a stable identity
        self.results: Dict[tuple, str] = {}
        self.lost_keys: set = set()
        # keys whose exit was classified as the ORIGINATING failure (not
        # a casualty of someone else's crash): only these charge their
        # host's crash budget — a cascade must not blocklist every host
        # whose healthy workers died from the collective error
        self.originators: set = set()
        self.host_crashes: Dict[str, int] = {}
        # workers a capacity-loss shrink dropped from the world: their
        # exit (the not-in-new-world path) is EXPECTED, not a crash
        self.expected_exits: set = set()
        # workers a preemption drain (or an autopilot action) planned
        # out of the world: EXPECTED exits recorded DRAINED — never
        # FAILURE, never a host_crashes charge, never blocklist evidence
        self.drained_exits: set = set()
        # drain-notice / action-request tokens already acted on; tokens
        # are (scope, key, payload) so the two KV scopes cannot collide
        self.handled_tokens: set = set()
        # tokens whose planned world was not viable yet (min_np, last
        # host, completion race): token -> (next_try, delay).  The world
        # can BECOME viable — discovery adds a host — so the request is
        # retried with backoff instead of burned.
        self.deferred_tokens: dict = {}
        self.threads: Dict[tuple, threading.Thread] = {}
        self.slot_by_key: Dict[tuple, object] = {}
        self.current_rank: Dict[tuple, int] = {}  # rank in CURRENT world
        self.slots = slots
        self.np = len(slots)
        # the job is DONE when every worker of the generation it started
        # with succeeds (minus crash-shrunken ones) — growth-spawned
        # stragglers whose world the survivors never joined (completion
        # raced the scale-up) must not hold the driver hostage
        self.essential_keys: List[tuple] = [(gen, s.rank) for s in slots]
        self.essential_gen = gen
        # the generation of the most recently PUBLISHED world — what the
        # workers' HVD_ELASTIC_GENERATION reads after they adopt it, and
        # therefore what their drain notices / action requests carry.
        # Tracked separately from essential_gen because in-place GROWTH
        # publishes a new generation (rank numbering unchanged — the
        # stable-assignment check guarantees it) without touching the
        # essential set.
        self.world_gen = gen
        # the generation of the last publish that CHANGED the rank
        # numbering: growth keeps numbering stable, so notices stamped
        # anywhere in [numbering_gen, world_gen] still name a valid
        # rank; in-place shrink recoveries compact ranks and bump it
        self.numbering_gen = gen
        self.coord_addr = coord_addr
        self.coord_port = coord_port
        self.spawn = None  # bound by _run_generation


#: autopilot action kinds the driver honors, mapped to whether the
#: target's host capacity is reserved for the full drain cooldown
#: (True: the host is suspect — place the replacement elsewhere) or
#: only the short restart window (False: the host is healthy, the
#: replacement should respawn onto it as soon as the chip is free).
#: ``quarantine`` (ISSUE 13) additionally BLOCKLISTS the host with the
#: action's evidence once the planned re-mesh succeeds — the one
#: planned exit that is held against the hardware, because silent data
#: corruption is a device property, not a scheduling accident.
_ACTION_KINDS = {"drain": True, "restart": False, "quarantine": True}


class ElasticDriver:
    def __init__(self, discovery: HostDiscovery, command: List[str],
                 min_np: int = 1, max_np: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 reset_limit: Optional[int] = None,
                 verbose: bool = False,
                 ckpt_dir: Optional[str] = None,
                 target_np: Optional[int] = None,
                 remote_exec=None,
                 world_secret: Optional[bytes] = None,
                 timestamp_output: bool = False,
                 start_timeout: Optional[float] = None,
                 elastic_timeout: Optional[float] = None) -> None:
        # remote_exec(slot, command, worker_env, events) -> rc replaces the
        # local/ssh exec when the cluster reaches hosts another way — e.g.
        # Spark tasks acting as host agents (spark/elastic.py). The
        # reference's analog is routing exec through its task services
        # instead of ssh (spark/gloo_run.py). world_secret lets such a
        # caller pre-share the world-doc HMAC key over its own trusted
        # channel instead of shipping it in worker envs over the network.
        self._remote_exec = remote_exec
        self._preshared_secret = world_secret
        self._timestamp_output = timestamp_output
        self._hosts = HostManager(discovery)
        self._command = command
        self._min_np = min_np
        self._max_np = max_np
        self._target_np = target_np
        self._env = dict(env if env is not None else os.environ)
        self._registry = WorkerStateRegistry(reset_limit)
        self._verbose = verbose
        self._ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="hvd_elastic_")
        # reference: --start-timeout bounds the initial min-host wait,
        # --elastic-timeout the re-scale waits after a generation ends
        # (an explicit 0 means "fail fast", so only None gets the default)
        self._start_timeout = 600.0 if start_timeout is None \
            else start_timeout
        self._elastic_timeout = 600.0 if elastic_timeout is None \
            else elastic_timeout
        self._stop = threading.Event()
        self._hosts_changed = threading.Event()
        self._generation = 0
        # world-document KV: survivors poll it at commit for growth resync.
        # Docs are HMAC-signed — workers apply env/coordinator changes from
        # them, and the KV port is open to the network.
        import secrets as _secrets
        import socket as _socket
        from horovod_tpu.runner.http_kv import KVStoreServer
        self._kv = KVStoreServer()
        self._kv.start()
        self._world_secret = self._preshared_secret or \
            _secrets.token_bytes(16)
        # the KV runs on THIS driver machine; remote workers need an
        # address that routes back here, not rank 0's host. gethostname,
        # not getfqdn: the latter can resolve to 'localhost' → ::1 while
        # the KV server is IPv4-only (see spark/elastic.py kv_addr)
        self._driver_addr = _socket.gethostname()

    # -- discovery thread (reference: driver.py:181-201) --------------------
    def _discovery_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._hosts.update_available_hosts():
                    self._hosts_changed.set()
            except Exception as e:  # discovery script hiccup: keep going
                get_logger().warning("host discovery failed: %s", e)
            time.sleep(DISCOVERY_INTERVAL_S)

    def _wait_for_min_hosts(self, timeout: float = 600.0) -> None:
        deadline = time.time() + timeout
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                self._hosts.update_available_hosts()
                consecutive_failures = 0
            except Exception as e:  # transient discovery hiccup: keep going
                consecutive_failures += 1
                get_logger().warning("host discovery failed: %s", e)
                if consecutive_failures >= 5:
                    # permanent misconfiguration (bad script path etc.):
                    # surface the real error instead of spinning to timeout
                    raise RuntimeError(
                        "host discovery failed 5 times in a row; check the "
                        f"discovery script: {e}") from e
            if self._hosts.slot_count() >= self._min_np:
                return
            time.sleep(DISCOVERY_INTERVAL_S)
        raise TimeoutError(
            f"needed {self._min_np} slots, found {self._hosts.slot_count()}")

    # -- world publication ---------------------------------------------------
    def _cap_np(self) -> int:
        return min(self._target_np or self._hosts.slot_count(),
                   self._max_np or self._hosts.slot_count(),
                   self._hosts.slot_count())

    def _publish_world(self, gen: int, slots, coord_addr: str,
                       coord_port: int, keyed_slots=None,
                       extra=None) -> None:
        """Publish a signed world doc. ``slots`` keys the doc by each
        slot's own (stable) rank — the growth case. ``keyed_slots``
        overrides with an explicit ``{lookup_rank: env}`` mapping — the
        shrink case, where survivors look themselves up by their OLD
        rank but adopt a smaller new one from the env.  ``extra`` merges
        additional signed fields into the doc (the ``drain`` stamp of a
        planned preemption re-mesh, which survivors use to label their
        re-mesh episode ``preemption_drain``)."""
        import json
        from horovod_tpu.elastic import world_doc_signature
        doc = {"generation": gen, "size": len(slots),
               "coord_addr": coord_addr, "coord_port": coord_port,
               "slots": keyed_slots if keyed_slots is not None
               else {str(s.rank): s.to_env() for s in slots}}
        if extra:
            doc.update(extra)
        doc["sig"] = world_doc_signature(self._world_secret, doc)
        body = json.dumps(doc).encode()
        self._kv.put("world", "current", body)
        self._push_world(body)

    def _push_world(self, body: bytes) -> None:
        """Push the published doc to every registered worker listener
        (reference: WorkerNotificationService push,
        ``runner/elastic/worker.py:46+``). Best-effort with short
        timeouts: a worker that missed the push still finds the doc by
        polling the KV at its next commit."""
        from horovod_tpu.runner.http_kv import kv_put

        def push(host: str, port: int) -> None:
            try:
                kv_put(host, port, "world", "current", body, timeout=5.0,
                       site="elastic.world_push")
            except OSError as e:
                get_logger().debug("world push to %s:%d failed: %s",
                                   host, port, e)

        for _rank, addr in self._kv.scope("notify").items():
            try:
                # the KV PUT surface is open to the network: malformed
                # registrations must be skipped, never crash the driver
                host, _, port = addr.decode().rpartition(":")
                port_num = int(port)
            except (UnicodeDecodeError, ValueError):
                get_logger().warning("ignoring malformed notify "
                                     "registration for rank %s", _rank)
                continue
            threading.Thread(target=push, args=(host, port_num),
                             daemon=True).start()

    # -- in-place crash recovery --------------------------------------------
    def _try_inplace_recovery(self, survivors, results, threads,
                              slot_by_key, current_rank, target_np,
                              host_crashes, charge_reset=True,
                              drain=None):
        """A worker died mid-generation: publish a new world around the
        SURVIVORS so they re-rendezvous IN PLACE (params stay in host
        memory, PIDs unchanged — reference: the reset loop after
        HorovodInternalError, ``common/elastic.py:151-175``) instead of
        paying a process restart + checkpoint reload. Replacement
        workers for the lost ranks are respawned onto free discovery
        capacity (the reference spawns missing ranks the same way); if
        capacity is gone (host dead / removed), the world SHRINKS to the
        survivors + whatever fits. Hosts that have already eaten as many
        crashes as they have slots get no replacements.

        Returns ``(new_slots, generation, replacement_slots, coord_addr,
        coord_port)`` on success, ``None`` when not viable — too few
        survivors+capacity, an essential worker already FINISHED (its
        result was published under the old generation; the restart path
        handles that completion race), or the --reset-limit budget is
        spent. ``charge_reset=False`` (planned capacity-loss shrinks)
        leaves the crash budget untouched — routine autoscaler
        downscales must never exhaust it."""
        if any(results.get(k) is not None or not threads[k].is_alive()
               for k in survivors):
            get_logger().info("in-place recovery not viable: an "
                              "essential worker already finished")
            return None
        # every survivor must have REGISTERED its notification listener
        # (done at its first elastic commit): that proves it runs an
        # elastic.run loop able to apply a new world doc. A worker still
        # inside hvd.init — or a job without elastic state at all — can
        # only be recovered by the generation-restart path; publishing a
        # world it will never read would deadlock the rendezvous.
        notify = {str(k) for k in self._kv.scope("notify")}
        unready = [k for k in survivors
                   if str(current_rank[k]) not in notify]
        if unready:
            get_logger().info(
                "in-place recovery not viable: survivors %s have no "
                "elastic listener registration (no committed elastic "
                "state)", [current_rank[k] for k in unready])
            return None
        surv_on: Dict[str, int] = {}
        for k in survivors:
            h = slot_by_key[k].hostname
            surv_on[h] = surv_on.get(h, 0) + 1
        # replacements go onto free capacity of healthy discovered hosts
        hosts_now = self._hosts.current_hosts()
        placement: List[str] = []
        n_repl = max(0, target_np - len(survivors))
        for h in hosts_now:
            if len(placement) >= n_repl:
                break
            if host_crashes.get(h.hostname, 0) >= h.slots:
                continue  # this host just keeps killing workers
            free = h.slots - surv_on.get(h.hostname, 0)
            placement.extend([h.hostname] * max(0, min(
                free, n_repl - len(placement))))
        new_np = len(survivors) + len(placement)
        if new_np < max(self._min_np, 1):
            get_logger().info(
                "in-place recovery not viable: %d survivors + %d "
                "replacements < min_np %d", len(survivors),
                len(placement), self._min_np)
            return None
        if charge_reset:
            # charged only once viability is established — a non-viable
            # attempt already pays for its generation restart
            self._registry.note_reset()
            if self._registry.reset_limit_reached():
                get_logger().info("in-place recovery not viable: reset "
                                  "limit reached")
                return None
        # per-host entries: survivors (in current-rank order) first, then
        # replacements — block assignment then aligns host-wise
        host_order: List[str] = []
        entries: Dict[str, list] = {}
        for k in sorted(survivors, key=lambda k: current_rank[k]):
            h = slot_by_key[k].hostname
            if h not in entries:
                host_order.append(h)
                entries[h] = []
            entries[h].append(k)
        for h in placement:
            if h not in entries:
                host_order.append(h)
                entries[h] = []
            entries[h].append(None)  # replacement marker
        hosts2 = [HostInfo(h, len(entries[h])) for h in host_order]
        new_slots = get_host_assignments(hosts2, new_np)
        flat = [e for h in host_order for e in entries[h]]
        keyed = {}
        replacements = []
        for e, ns in zip(flat, new_slots):
            if e is None:
                replacements.append(ns)
                continue
            assert ns.hostname == slot_by_key[e].hostname, (e, ns)
            # survivors look the doc up by the rank they CURRENTLY hold;
            # the env inside hands them their new one
            keyed[str(current_rank[e])] = ns.to_env()
            current_rank[e] = ns.rank
        coord_port = free_port()
        coord_addr = "127.0.0.1" if new_slots[0].hostname in (
            "localhost", "127.0.0.1") else new_slots[0].hostname
        gen = self._generation
        self._generation += 1
        get_logger().info(
            "elastic generation %d (%s): np=%d "
            "(%d survivors + %d replacements)", gen,
            "planned preemption drain" if drain
            else "in-place crash recovery", new_np,
            len(survivors), len(replacements))
        extra = {"drain": drain} if drain else {}
        from horovod_tpu import tracing
        if drain is None:
            # a REACTIVE recovery has no inbound context to continue
            # (the planned path's drain stamp carries the notice's) —
            # root one here so every survivor's re-mesh episode still
            # shares a single trace id with this publish
            ctx = tracing.new_trace("elastic")
            if ctx is not None:
                extra["traceparent"] = ctx.traceparent
        self._publish_world(gen, new_slots, coord_addr, coord_port,
                            keyed_slots=keyed, extra=extra or None)
        # driver-side half of the re-mesh timeline: the survivors
        # measure their own phases (hvd_remesh_seconds); the driver
        # stamps WHEN it published the recovery world, so a merged
        # flight view can attribute the workers' failure_detect wait
        from horovod_tpu.diagnostics.flight_recorder import record_event
        doc_ctx = tracing.decode((drain or {}).get("traceparent")) \
            if drain else ctx
        record_event("remesh_driver_published", generation=gen,
                     np=new_np, survivors=len(survivors),
                     replacements=len(replacements),
                     charge_reset=charge_reset,
                     **tracing.fields(doc_ctx))
        # registrations are stale the moment ranks renumber: survivors
        # re-register at their first commit in the new world, and a crash
        # BEFORE that commit conservatively takes the restart path
        self._kv.clear("notify")
        # so are drain notices: a notice names the rank its publisher
        # held in the OLD numbering — left behind, an unhandled notice
        # would match whichever innocent worker inherits that rank
        self._kv.clear("drain")
        # and so are autopilot action requests, for the same reason: the
        # rank an action targets is only meaningful in the numbering
        # whose finding fired it
        self._kv.clear("action")
        return new_slots, gen, replacements, coord_addr, coord_port

    # -- drain notices & autopilot actions (poll-loop handlers) -------------
    def _scan_scope(self, g: _GenRuntime, scope: str, label: str):
        """THE one validation core for worker→driver request scopes
        (drain notices and autopilot actions share it — a fix to the
        gating below must never apply to one and silently diverge the
        other).  For each entry: skip already-handled tokens and those
        inside their no-viable-world backoff window; burn (never retry)
        malformed JSON; require the stamped generation inside
        ``[numbering_gen, world_gen]`` — published under another rank
        NUMBERING, matching it against the current one could doom an
        innocent worker, while growth publishes bump the generation but
        keep the numbering (stable-assignment check) so anything since
        the last RENUMBERING publish is still valid; out-of-window
        entries are left unhandled (not burned): the next re-mesh
        clears the scope, worst case the worker dies reactively.
        Finally resolve the named rank to a live essential worker; a
        miss (already gone or renumbered) burns the token as stale.
        Returns ``[(token, doc, origin key, named rank)]``."""
        import json as _json
        out = []
        for key, raw in self._kv.scope(scope).items():
            token = (scope, key, raw)
            if token in g.handled_tokens:
                continue
            deferred = g.deferred_tokens.get(token)
            if deferred and deferred[0] > time.monotonic():
                continue  # no-viable-world backoff window
            try:
                doc = _json.loads(raw)
                if not isinstance(doc, dict):
                    raise TypeError(f"{label} is not an object")
                nrank = int(doc.get("rank"))
                ngen = int(doc.get("generation", -1))
            except (ValueError, TypeError):
                g.handled_tokens.add(token)  # never retried
                get_logger().warning(
                    "ignoring malformed %s %r", label, key)
                continue
            if not g.numbering_gen <= ngen <= g.world_gen:
                continue  # another numbering (docstring above)
            origin = next(
                (k for k in g.essential_keys
                 if g.current_rank.get(k) == nrank
                 and g.results.get(k) is None
                 and g.threads[k].is_alive()), None)
            if origin is None:
                g.handled_tokens.add(token)
                continue  # already gone or renumbered: stale
            out.append((token, doc, origin, nrank))
        return out

    def _scan_drain_notices(self, g: _GenRuntime):
        """Collect actionable drain notices from the KV ``drain`` scope
        (docs/ELASTIC.md "Proactive drain & preemption"): a doomed
        worker's PreemptionWatcher published ``drain/<rank>``; plan its
        world out AROUND it instead of waiting for the death +
        transport-timeout detection the reactive path pays.  Returns
        ``(doomed keys, notice meta, tokens)``."""
        doomed: set = set()
        notice_meta: list = []
        tokens: list = []
        for token, notice, origin, nrank in self._scan_scope(
                g, "drain", "drain notice"):
            tokens.append(token)
            if notice.get("scope") == "host":
                # host-wide maintenance dooms every worker there
                h = g.slot_by_key[origin].hostname
                doomed |= {k for k in g.essential_keys
                           if g.slot_by_key[k].hostname == h
                           and g.results.get(k) is None
                           and g.threads[k].is_alive()}
            else:
                doomed.add(origin)
            entry = {"rank": nrank,
                     "host": g.slot_by_key[origin].hostname,
                     "source": notice.get("source", "unknown")}
            if isinstance(notice.get("traceparent"), str):
                # the publisher's trace context rides the notice doc;
                # the handling and the published world continue it
                entry["traceparent"] = notice["traceparent"]
            notice_meta.append(entry)
        return doomed, notice_meta, tokens

    def _scan_action_requests(self, g: _GenRuntime):
        """Collect actionable autopilot requests from the KV ``action``
        scope (ISSUE 12; docs/OBSERVABILITY.md "Autopilot"): a policy
        engine's fired remediation asked the driver to plan a worker
        out of the world — ``drain`` (sick host: reserve its capacity
        for the full cooldown) or ``restart`` (healthy host: final
        durable commit, then respawn in place after the short restart
        window).  Validation is :meth:`_scan_scope`, shared with the
        drain notices; an unknown action kind is burned here.  Returns
        ``{kind: (doomed keys, meta, tokens)}``."""
        groups = {kind: (set(), [], []) for kind in _ACTION_KINDS}
        for token, req, origin, nrank in self._scan_scope(
                g, "action", "autopilot action"):
            kind = req.get("action")
            if kind not in _ACTION_KINDS:
                g.handled_tokens.add(token)
                get_logger().warning(
                    "ignoring autopilot action %r with unknown kind %r",
                    token[1], kind)
                continue
            doomed, meta, tokens = groups[kind]
            doomed.add(origin)
            tokens.append(token)
            entry = {"rank": nrank,
                     "host": g.slot_by_key[origin].hostname,
                     "source": "autopilot",
                     "policy": req.get("policy"),
                     "action": kind}
            if isinstance(req.get("traceparent"), str):
                # finding → decision → action doc: the trace continues
                # through the driver's handling into the re-mesh
                entry["traceparent"] = req["traceparent"]
            if isinstance(req.get("evidence"), dict):
                # quarantine requests carry the canary digests that
                # convicted the rank — recorded with the blocklist
                entry["evidence"] = req["evidence"]
            meta.append(entry)
        return groups

    def _plan_world_out(self, g: _GenRuntime, doomed: set,
                        notice_meta: list, tokens: list,
                        cooldown: float, event_kind: str):
        """Plan the current world around ``doomed`` (shared by drain
        notices and autopilot actions): reserve the doomed capacity,
        mark the exits DRAINED, publish the survivor world, spawn
        replacements onto free capacity — or, when no viable world
        exists, REVERT every piece of that bookkeeping and retry the
        request with backoff (reactive recovery covers an actual
        death).  Returns ``"planned"`` when the survivor world was
        published, ``"retry"`` when no viable world existed and the
        request was re-armed with backoff — both truthy: the tick is
        consumed and the caller ``continue``s — or False when the
        request was deferred untouched (workers still registering
        their elastic listeners)."""
        # the planned path needs every involved worker able to APPLY a
        # world doc (elastic listener registered, i.e. it has committed
        # once).  A request racing the job's first commits — a
        # preemption can announce itself during hvd.init — is DEFERRED
        # to a later tick, not burned on a generation restart.
        notify = {str(r) for r in self._kv.scope("notify")}
        involved = set(doomed) | {
            k for k in g.essential_keys
            if k not in doomed and g.results.get(k) is None
            and g.threads[k].is_alive()}
        if any(str(g.current_rank[k]) not in notify for k in involved):
            return False
        g.handled_tokens.update(tokens)
        # the driver's handling is a CHILD span of the notice/action
        # that asked for it (docs/OBSERVABILITY.md "Causal tracing");
        # the drain-stamped world carries the context onward so every
        # survivor's re-mesh episode joins the same trace
        from horovod_tpu import tracing
        hctx = None
        for m in notice_meta:
            hctx = tracing.child(
                tracing.decode(m.get("traceparent")), "elastic")
            if hctx is not None:
                break
        by_host: Dict[str, int] = {}
        for k in doomed:
            h = g.slot_by_key[k].hostname
            by_host[h] = by_host.get(h, 0) + 1
        for h, n in by_host.items():
            # reserve the doomed capacity so replacement placement
            # cannot land back on it before the cooldown re-admits it
            # (a drain's host announced its own death; a restart's is
            # healthy and re-admits within seconds)
            self._hosts.drain(h, n, cooldown)
        with g.fail_lock:
            # BEFORE the publish (same reason as the shrink path): the
            # doomed worker can read the pushed doc and exit before
            # this loop resumes, and that exit is DRAINED, never a
            # crash
            g.expected_exits.update(doomed)
            g.drained_exits.update(doomed)
        survivors = [k for k in g.essential_keys if k not in doomed]
        from horovod_tpu.diagnostics.flight_recorder import record_event
        record_event(
            event_kind,
            notices=notice_meta,
            drained_ranks=sorted(g.current_rank[k] for k in doomed),
            hosts=sorted(by_host), cooldown_s=cooldown,
            **tracing.fields(hctx))
        get_logger().warning(
            "%s %s: planning world around doomed rank(s) %s (hosts %s "
            "reserved for %.0fs)", event_kind, notice_meta,
            sorted(g.current_rank[k] for k in doomed),
            sorted(by_host), cooldown)
        recovered = self._try_inplace_recovery(
            survivors, g.results, g.threads, g.slot_by_key,
            g.current_rank, self._cap_np(), g.host_crashes,
            charge_reset=False,
            drain={"ranks": sorted(g.current_rank[k] for k in doomed),
                   "hosts": sorted(by_host),
                   "sources": sorted({m["source"]
                                      for m in notice_meta}),
                   **({"traceparent": hctx.traceparent}
                      if hctx is not None else {})})
        if recovered is None:
            # no viable planned world (the doomed host was the last
            # one, min_np would be violated, or a completion race): the
            # request is ADVISORY — the worker has not died, and may
            # never.  Tearing the generation down here would turn
            # advance notice into a guaranteed restart the reactive
            # path never pays, so revert the bookkeeping and fall back
            # to reactive recovery instead.
            with g.fail_lock:
                g.expected_exits.difference_update(doomed)
                g.drained_exits.difference_update(doomed)
                # a doomed worker that exited DURING the failed
                # planning attempt was classified an expected DRAINED
                # exit, so run_slot never marked it lost — re-mark it
                # here or no recovery would ever be planned for a
                # genuinely dead worker and the generation would wedge
                gone = [k for k in doomed
                        if g.results.get(k) is not None]
                if gone:
                    g.lost_keys.update(gone)
                    g.worker_lost.set()
            for h, n in by_host.items():
                self._hosts.undrain(h, n)
            # un-burn the requests: the world can BECOME viable
            # (discovery adds a host) before the doomed worker dies,
            # and a drain watcher is latched after its one publish —
            # without the retry the advance notice would be permanently
            # lost.  Backoff bounds the replanning churn.
            for t in tokens:
                g.handled_tokens.discard(t)
                delay = min(
                    g.deferred_tokens.get(t, (0.0, 1.0))[1] * 2, 30.0)
                g.deferred_tokens[t] = (time.monotonic() + delay, delay)
            get_logger().warning(
                "no viable planned world for %s %s; retrying with "
                "backoff, reactive recovery covers an actual death",
                event_kind, notice_meta)
            return "retry"
        # rebind the coordinator BEFORE spawning: run_slot reads the
        # runtime's coord fields at call time, and a replacement
        # pointed at the dead world's port would never find the mesh
        new_slots2, rec_gen, replacements, g.coord_addr, \
            g.coord_port = recovered
        for s in replacements:
            g.spawn(s, rec_gen)
        g.essential_keys = survivors + [
            (rec_gen, s.rank) for s in replacements]
        g.essential_gen = g.world_gen = g.numbering_gen = rec_gen
        g.slots = new_slots2
        g.np = len(new_slots2)
        return "planned"

    def _poll_drain_notices(self, g: _GenRuntime) -> bool:
        doomed, notice_meta, tokens = self._scan_drain_notices(g)
        if not doomed:
            return False
        return self._plan_world_out(g, doomed, notice_meta, tokens,
                                    drain_cooldown_s(),
                                    "drain_notice_handled")

    def _poll_action_requests(self, g: _GenRuntime) -> bool:
        groups = self._scan_action_requests(g)
        for kind, reserve_full in _ACTION_KINDS.items():
            doomed, meta, tokens = groups[kind]
            if not doomed:
                continue
            cooldown = drain_cooldown_s() if reserve_full \
                else restart_cooldown_s()
            result = self._plan_world_out(g, doomed, meta, tokens,
                                          cooldown,
                                          "autopilot_action_handled")
            if not result:
                continue  # deferred: try the other action kinds
            if kind == "quarantine" and result == "planned":
                # ISSUE 13: unlike a preemption drain, a quarantine IS
                # evidence against the hardware — blocklist the
                # divergent rank's host, with the canary digests that
                # convicted it on the record (re-admitted only by the
                # HVD_TPU_BLOCKLIST_COOLDOWN_S expiry)
                from horovod_tpu.diagnostics.flight_recorder import (
                    record_event)
                for m in meta:
                    self._hosts.blacklist(m["host"])
                    record_event("quarantine_blocklisted",
                                 host=m["host"], rank=m["rank"],
                                 policy=m.get("policy"),
                                 evidence=m.get("evidence"))
                    get_logger().error(
                        "quarantine: host %s (rank %d) blocklisted for "
                        "replica divergence — policy %s, evidence %s",
                        m["host"], m["rank"], m.get("policy"),
                        m.get("evidence"))
            return True
        return False

    def _recover_lost_workers(self, g: _GenRuntime) -> None:
        """A worker crashed mid-generation: recover the world in place
        (or set the failure flag for the generation-restart backstop).
        Lets a correlated burst finish dying before planning: the other
        ranks of a doomed host group are typically milliseconds behind
        the first exit, and one settled re-mesh beats a cascade of
        partial ones."""
        time.sleep(loss_settle_s())
        with g.fail_lock:
            g.worker_lost.clear()
            lost_now = set(g.lost_keys)
            blamed = lost_now & g.originators
            # this round handles exactly lost_now; clearing lets the
            # NEXT crash classify as an originator again and keeps
            # host_crashes from re-counting old losses (originators
            # pruned alongside: keys are per-instance, a handled one
            # can never recur)
            g.lost_keys.clear()
            g.originators -= lost_now
            survivors = [k for k in g.essential_keys
                         if k not in lost_now]
        # only the originating FAILURE charges its host's crash budget;
        # casualties are fallout, not evidence the host is bad (their
        # replacement still respawns below)
        for k in blamed:
            h = g.slot_by_key[k].hostname
            g.host_crashes[h] = g.host_crashes.get(h, 0) + 1
        recovered = self._try_inplace_recovery(
            survivors, g.results, g.threads, g.slot_by_key,
            g.current_rank, g.np, g.host_crashes)
        if recovered is None:
            g.failure.set()  # not viable: generation-restart path
            return
        # rebind the coordinator BEFORE spawning (see _plan_world_out)
        new_slots2, rec_gen, replacements, g.coord_addr, \
            g.coord_port = recovered
        for s in replacements:
            g.spawn(s, rec_gen)
        g.essential_keys = survivors + [
            (rec_gen, s.rank) for s in replacements]
        g.essential_gen = g.world_gen = g.numbering_gen = rec_gen
        g.slots = new_slots2
        g.np = len(new_slots2)

    def _apply_membership_change(self, g: _GenRuntime) -> None:
        """Discovery changed the host set mid-generation: shrink in
        place (capacity loss), grow in place (new slots spawned into
        the RUNNING generation), or set the teardown flag for a
        generation restart when neither is safe."""
        new_hosts = self._hosts.current_hosts()
        new_np = self._cap_np()
        old_hostnames = {s.hostname for s in g.slots}
        still_there = old_hostnames.issubset(
            {h.hostname for h in new_hosts})
        if not still_there or new_np < g.np:
            # capacity loss: keep the remaining workers IN PLACE when
            # they can all apply a world doc (elastic state committed
            # at least once); dropped workers exit via the
            # not-in-new-world path at their next commit. Anything
            # else — a finished essential, unregistered workers, too
            # little capacity — takes the generation-restart path.
            if any(g.results.get(k) is not None
                   for k in g.essential_keys):
                g.teardown.set()
                return
            # keep workers per host up to that host's NEW slot count
            # (the downscaled host must actually lose workers) in
            # current-rank order, capped at the new world size
            new_caps = {h.hostname: h.slots for h in new_hosts}
            alive = [k for k in g.essential_keys
                     if g.threads[k].is_alive()]
            kept, used = [], {}
            for k in sorted(alive, key=lambda k: g.current_rank[k]):
                h = g.slot_by_key[k].hostname
                if len(kept) < new_np and \
                        used.get(h, 0) < new_caps.get(h, 0):
                    kept.append(k)
                    used[h] = used.get(h, 0) + 1
            dropped = [k for k in g.essential_keys if k not in kept]
            with g.fail_lock:
                # BEFORE the publish: a dropped worker can read the
                # pushed doc and exit before this loop resumes, and
                # that exit must not be classified as a crash
                g.expected_exits.update(dropped)
            recovered = self._try_inplace_recovery(
                kept, g.results, g.threads, g.slot_by_key,
                g.current_rank, new_np, g.host_crashes,
                charge_reset=False)
            if recovered is None:
                g.teardown.set()
                return
            new_slots2, rec_gen, replacements, g.coord_addr, \
                g.coord_port = recovered
            for s in replacements:
                g.spawn(s, rec_gen)
            g.essential_keys = kept + [(rec_gen, s.rank)
                                       for s in replacements]
            g.essential_gen = g.world_gen = g.numbering_gen = rec_gen
            g.slots = new_slots2
            g.np = len(new_slots2)
            return
        if new_np <= g.np:
            return  # capacity we are not using anyway
        # GROWTH: stable assignment keeps existing ranks; spawn only
        # the new slots, publish the new world for survivor resync
        new_slots = get_host_assignments(new_hosts, new_np)
        if not all(ns.rank == s.rank and ns.hostname == s.hostname
                   for ns, s in zip(new_slots, g.slots)):
            # assignment reshuffled existing ranks (host reordering):
            # in-place resync would double-assign ranks — restart
            get_logger().warning(
                "growth reshuffled existing ranks; falling back to a "
                "generation restart")
            g.teardown.set()
            return
        g.coord_port = free_port()  # fresh rendezvous for the new world
        gen = self._generation
        self._generation += 1
        get_logger().info(
            "elastic generation %d (growth, in-place): np=%d->%d",
            gen, g.np, new_np)
        self._publish_world(gen, new_slots, g.coord_addr, g.coord_port)
        g.world_gen = gen  # survivors adopt this gen; notices carry it
        for s in new_slots[g.np:]:
            g.spawn(s, gen)
        g.slots = new_slots
        g.np = new_np

    # -- one generation ------------------------------------------------------
    def _run_generation(self) -> str:
        """Launch workers for the current host set; returns SUCCESS /
        FAILURE / 'HOSTS_CHANGED'. Growth extends the RUNNING generation
        (new world published to the KV, survivors resync at commit);
        shrink/failure tears it down for a restart."""
        hosts = self._hosts.current_hosts()
        np = self._cap_np()
        slots = get_host_assignments(hosts, np)
        coord_port = free_port()
        coord_addr = "127.0.0.1" if slots[0].hostname in (
            "localhost", "127.0.0.1") else slots[0].hostname
        self._registry.reset(np)
        # drop listener registrations from the previous generation: its
        # processes are gone, and pushing signed world docs at dead (or
        # recycled) host:port addresses wastes a thread per publish and
        # could hand the doc to an unrelated process. This generation's
        # workers re-register at their first commit.
        self._kv.clear("notify")
        # stale drain notices die with their generation too: the rank a
        # notice names is only meaningful in the world that published it,
        # and the doomed HOST is already held out by its HostManager
        # drain reservation regardless
        self._kv.clear("drain")
        # autopilot action requests die with their generation too: the
        # rank a request targets is only meaningful in the world whose
        # finding fired it
        self._kv.clear("action")
        self._hosts_changed.clear()
        gen = self._generation
        self._generation += 1
        get_logger().info("elastic generation %d: np=%d hosts=%s", gen, np,
                          [h.hostname for h in hosts])
        self._publish_world(gen, slots, coord_addr, coord_port)

        g = _GenRuntime(slots, gen, coord_addr, coord_port)

        def run_slot(slot, slot_gen):
            extra_env = {
                "HVD_TPU_ELASTIC": "1",
                "HVD_ELASTIC_GENERATION": str(slot_gen),
                "HVD_ELASTIC_CKPT": self._ckpt_dir,
                "HVD_ELASTIC_SECRET": self._world_secret.hex(),
                "HVD_ELASTIC_KV": f"127.0.0.1:{self._kv.port}"
                if slot.hostname in ("localhost", "127.0.0.1")
                else f"{self._driver_addr}:{self._kv.port}"}
            prefix = f"[{slot.rank}]" if self._verbose else ""
            if self._remote_exec is not None:
                # agent transport: ship the RAW worker command + env; the
                # agent on slot.hostname execs it locally (no ssh wrap)
                from horovod_tpu.runner.exec_run import build_worker_env
                wenv = build_worker_env(slot, g.coord_addr, g.coord_port,
                                        self._env)
                wenv.update(extra_env)
                if self._preshared_secret is not None:
                    # the caller distributed the secret over its own
                    # trusted channel; keep it off the wire
                    wenv.pop("HVD_ELASTIC_SECRET", None)
                rc = self._remote_exec(slot, self._command, wenv,
                                       [g.failure, g.teardown])
            else:
                # local-vs-ssh dispatch shared with the static launcher so
                # multi-host elastic jobs actually place workers remotely
                cmd, env = slot_command(
                    slot, self._command, g.coord_addr, g.coord_port,
                    self._env, extra_env=extra_env)
                rc = safe_execute(cmd, env=env, prefix=prefix,
                                  events=[g.failure, g.teardown],
                                  timestamp=self._timestamp_output)
            key = (slot_gen, slot.rank)
            if rc == 0:
                g.results[key] = SUCCESS
                self._registry.record(slot.rank, slot.hostname, SUCCESS)
                return
            # Distinguish the ORIGINATING failure from its fallout:
            # workers the driver tore down, and CASUALTIES — workers that
            # died from the collective error the originator caused (a job
            # without elastic state has no way to ride out a peer loss).
            # Only the originator counts as FAILURE, so the blacklist and
            # the restart decision see one crash, not a cascade. A crash
            # does not fail the generation outright anymore: the main
            # loop first tries to recover the world in place.
            with g.fail_lock:
                torn_down = g.failure.is_set() or g.teardown.is_set()
                expected = key in g.expected_exits
                casualty = bool(g.lost_keys) and not torn_down \
                    and not expected
                if not torn_down and not expected:
                    g.lost_keys.add(key)
                    if not casualty:
                        g.originators.add(key)
                    g.worker_lost.set()
                # classification is atomic with the membership checks:
                # _plan_world_out's no-viable-world revert edits these
                # sets under the same lock and must observe either a
                # fully recorded exit or none at all
                if key in g.drained_exits:
                    state = DRAINED
                elif torn_down or casualty or expected:
                    state = TERMINATED
                else:
                    state = FAILURE
                g.results[key] = state
            self._registry.record(slot.rank, slot.hostname, state)

        def spawn(slot, slot_gen):
            key = (slot_gen, slot.rank)
            t = threading.Thread(target=run_slot, args=(slot, slot_gen),
                                 daemon=True)
            g.threads[key] = t
            g.slot_by_key[key] = slot
            g.current_rank[key] = slot.rank
            t.start()

        g.spawn = spawn
        for s in slots:
            spawn(s, gen)

        while any(t.is_alive() for t in g.threads.values()):
            time.sleep(0.25)
            if not g.failure.is_set() and not g.teardown.is_set() and \
                    all(g.results.get(k) == SUCCESS
                        for k in g.essential_keys):
                # survivors finished; kill growth stragglers still waiting
                # for a rendezvous that will never complete
                g.teardown.set()
            # -- a worker crashed: recover the world in place --------------
            if g.worker_lost.is_set() and not g.failure.is_set() and \
                    not g.teardown.is_set():
                self._recover_lost_workers(g)
                continue
            if not g.failure.is_set() and not g.teardown.is_set():
                # -- a preemption/maintenance drain notice arrived ---------
                if self._poll_drain_notices(g):
                    continue
                # -- an autopilot action request arrived (ISSUE 12) --------
                if self._poll_action_requests(g):
                    continue
            if g.failure.is_set() or not self._hosts_changed.is_set():
                continue
            # -- membership changed mid-generation -------------------------
            self._hosts_changed.clear()
            self._apply_membership_change(g)

        ess_ok = all(
            g.results.get(k) == SUCCESS for k in g.essential_keys)
        if ess_ok:
            # only the ESSENTIAL workers are guaranteed complete —
            # in-place growth may have raised np while its stragglers
            # were torn down after the survivors finished in the old
            # world, and crash-shrunken workers' FAILURE records were
            # absorbed by the in-place re-mesh
            self._final_np = len(g.essential_keys)
            self._final_gen = g.essential_gen
            return SUCCESS
        if (g.teardown.is_set() or self._hosts_changed.is_set()) and \
                self._registry.count(FAILURE) == 0:
            return "HOSTS_CHANGED"
        if self._registry.count(FAILURE) > 0:
            for host, n in self._registry.failed_hosts().items():
                # a host whose every worker failed is blacklisted
                # (reference: driver blacklist, driver.py:297-313)
                host_slots = sum(1 for s in g.slots
                                 if s.hostname == host)
                if n >= host_slots:
                    self._hosts.blacklist(host)
            return FAILURE
        self._final_np = len(g.essential_keys)
        self._final_gen = g.essential_gen
        return SUCCESS

    @property
    def final_np(self) -> Optional[int]:
        """World size of the generation that completed successfully (None
        until then) — callers collecting per-rank artifacts use it to
        ignore leftovers from aborted generations."""
        return getattr(self, "_final_np", None)

    @property
    def final_generation(self) -> Optional[int]:
        """Generation number the completed ranks were launched with
        (their ``HVD_ELASTIC_GENERATION``) — pairs with final_np for
        generation-scoped artifact collection."""
        return getattr(self, "_final_gen", None)

    # -- main loop -----------------------------------------------------------
    def run(self) -> int:
        self._wait_for_min_hosts(timeout=self._start_timeout)
        disc = threading.Thread(target=self._discovery_loop, daemon=True)
        disc.start()
        try:
            while True:
                result = self._run_generation()
                if result == SUCCESS:
                    return 0
                if self._registry.reset_limit_reached():
                    get_logger().error(
                        "elastic reset limit reached after %d generations",
                        self._registry.reset_count)
                    return 1
                # wait until we have enough usable slots again
                try:
                    self._wait_for_min_hosts(timeout=self._elastic_timeout)
                except TimeoutError:
                    return 1
        finally:
            self._stop.set()
            disc.join(timeout=3)
            self._kv.stop()


def run_elastic(discovery: HostDiscovery, np: Optional[int],
                command: List[str],
                min_np: int = 1, max_np: Optional[int] = None,
                env: Optional[Dict[str, str]] = None,
                verbose: bool = False,
                reset_limit: Optional[int] = None,
                timestamp_output: bool = False,
                start_timeout: Optional[float] = None,
                elastic_timeout: Optional[float] = None) -> int:
    driver = ElasticDriver(discovery, command, min_np=min_np, max_np=max_np,
                           env=env, verbose=verbose, reset_limit=reset_limit,
                           target_np=np, timestamp_output=timestamp_output,
                           start_timeout=start_timeout,
                           elastic_timeout=elastic_timeout)
    return driver.run()
