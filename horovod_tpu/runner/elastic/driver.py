"""Elastic driver: discovery-driven launch/relaunch with blacklist and
rank-stable assignments.

Reference: ``horovod/runner/elastic/driver.py`` (``ElasticDriver``: discovery
thread :181-201, stable rank assignment :233-275, worker spawn per slot
:277-295, blacklist + exit handling :297-313).

TPU-native design:

* **Failures and shrink are process-restart based**: the driver terminates
  the generation, recomputes assignments (stable ranks, failed hosts
  blacklisted), and relaunches; workers resume from their last committed
  :class:`horovod_tpu.elastic.State` checkpoint (``HVD_ELASTIC_CKPT``).
* **Growth keeps survivors running** (VERDICT r1 #6): when discovery only
  ADDS capacity, the driver publishes a new world document (generation,
  size, per-rank env, fresh rendezvous port) to its KV server and spawns
  workers for the new slots only. Survivors pick the update up at their
  next ``state.commit()`` (``HostsUpdatedInterrupt`` → in-place re-init,
  no process restart: no re-import, no spawn, parameters stay in host
  memory — only the core re-rendezvous and the XLA recompile that any
  world change requires). Ranks are stable under growth, so survivors
  keep their rank and shard assignments.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, List, Optional

from horovod_tpu.common.logging import get_logger
from horovod_tpu.runner.elastic.discovery import HostDiscovery, HostManager
from horovod_tpu.runner.elastic.registration import (FAILURE, SUCCESS,
                                                     TERMINATED,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.exec_run import (free_port, slot_command)
from horovod_tpu.runner.hosts import get_host_assignments
from horovod_tpu.runner.safe_exec import safe_execute

DISCOVERY_INTERVAL_S = 1.0


class ElasticDriver:
    def __init__(self, discovery: HostDiscovery, command: List[str],
                 min_np: int = 1, max_np: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 reset_limit: Optional[int] = None,
                 verbose: bool = False,
                 ckpt_dir: Optional[str] = None,
                 target_np: Optional[int] = None,
                 remote_exec=None,
                 world_secret: Optional[bytes] = None,
                 timestamp_output: bool = False,
                 start_timeout: Optional[float] = None,
                 elastic_timeout: Optional[float] = None) -> None:
        # remote_exec(slot, command, worker_env, events) -> rc replaces the
        # local/ssh exec when the cluster reaches hosts another way — e.g.
        # Spark tasks acting as host agents (spark/elastic.py). The
        # reference's analog is routing exec through its task services
        # instead of ssh (spark/gloo_run.py). world_secret lets such a
        # caller pre-share the world-doc HMAC key over its own trusted
        # channel instead of shipping it in worker envs over the network.
        self._remote_exec = remote_exec
        self._preshared_secret = world_secret
        self._timestamp_output = timestamp_output
        self._hosts = HostManager(discovery)
        self._command = command
        self._min_np = min_np
        self._max_np = max_np
        self._target_np = target_np
        self._env = dict(env if env is not None else os.environ)
        self._registry = WorkerStateRegistry(reset_limit)
        self._verbose = verbose
        self._ckpt_dir = ckpt_dir or tempfile.mkdtemp(prefix="hvd_elastic_")
        # reference: --start-timeout bounds the initial min-host wait,
        # --elastic-timeout the re-scale waits after a generation ends
        # (an explicit 0 means "fail fast", so only None gets the default)
        self._start_timeout = 600.0 if start_timeout is None \
            else start_timeout
        self._elastic_timeout = 600.0 if elastic_timeout is None \
            else elastic_timeout
        self._stop = threading.Event()
        self._hosts_changed = threading.Event()
        self._generation = 0
        # world-document KV: survivors poll it at commit for growth resync.
        # Docs are HMAC-signed — workers apply env/coordinator changes from
        # them, and the KV port is open to the network.
        import secrets as _secrets
        import socket as _socket
        from horovod_tpu.runner.http_kv import KVStoreServer
        self._kv = KVStoreServer()
        self._kv.start()
        self._world_secret = self._preshared_secret or \
            _secrets.token_bytes(16)
        # the KV runs on THIS driver machine; remote workers need an
        # address that routes back here, not rank 0's host. gethostname,
        # not getfqdn: the latter can resolve to 'localhost' → ::1 while
        # the KV server is IPv4-only (see spark/elastic.py kv_addr)
        self._driver_addr = _socket.gethostname()

    # -- discovery thread (reference: driver.py:181-201) --------------------
    def _discovery_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._hosts.update_available_hosts():
                    self._hosts_changed.set()
            except Exception as e:  # discovery script hiccup: keep going
                get_logger().warning("host discovery failed: %s", e)
            time.sleep(DISCOVERY_INTERVAL_S)

    def _wait_for_min_hosts(self, timeout: float = 600.0) -> None:
        deadline = time.time() + timeout
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                self._hosts.update_available_hosts()
                consecutive_failures = 0
            except Exception as e:  # transient discovery hiccup: keep going
                consecutive_failures += 1
                get_logger().warning("host discovery failed: %s", e)
                if consecutive_failures >= 5:
                    # permanent misconfiguration (bad script path etc.):
                    # surface the real error instead of spinning to timeout
                    raise RuntimeError(
                        "host discovery failed 5 times in a row; check the "
                        f"discovery script: {e}") from e
            if self._hosts.slot_count() >= self._min_np:
                return
            time.sleep(DISCOVERY_INTERVAL_S)
        raise TimeoutError(
            f"needed {self._min_np} slots, found {self._hosts.slot_count()}")

    # -- world publication ---------------------------------------------------
    def _cap_np(self) -> int:
        return min(self._target_np or self._hosts.slot_count(),
                   self._max_np or self._hosts.slot_count(),
                   self._hosts.slot_count())

    def _publish_world(self, gen: int, slots, coord_addr: str,
                       coord_port: int) -> None:
        import json
        from horovod_tpu.elastic import world_doc_signature
        doc = {"generation": gen, "size": len(slots),
               "coord_addr": coord_addr, "coord_port": coord_port,
               "slots": {str(s.rank): s.to_env() for s in slots}}
        doc["sig"] = world_doc_signature(self._world_secret, doc)
        body = json.dumps(doc).encode()
        self._kv.put("world", "current", body)
        self._push_world(body)

    def _push_world(self, body: bytes) -> None:
        """Push the published doc to every registered worker listener
        (reference: WorkerNotificationService push,
        ``runner/elastic/worker.py:46+``). Best-effort with short
        timeouts: a worker that missed the push still finds the doc by
        polling the KV at its next commit."""
        from horovod_tpu.runner.http_kv import kv_put

        def push(host: str, port: int) -> None:
            try:
                kv_put(host, port, "world", "current", body, timeout=5.0)
            except OSError as e:
                get_logger().debug("world push to %s:%d failed: %s",
                                   host, port, e)

        for _rank, addr in self._kv.scope("notify").items():
            try:
                # the KV PUT surface is open to the network: malformed
                # registrations must be skipped, never crash the driver
                host, _, port = addr.decode().rpartition(":")
                port_num = int(port)
            except (UnicodeDecodeError, ValueError):
                get_logger().warning("ignoring malformed notify "
                                     "registration for rank %s", _rank)
                continue
            threading.Thread(target=push, args=(host, port_num),
                             daemon=True).start()

    # -- one generation ------------------------------------------------------
    def _run_generation(self) -> str:
        """Launch workers for the current host set; returns SUCCESS /
        FAILURE / 'HOSTS_CHANGED'. Growth extends the RUNNING generation
        (new world published to the KV, survivors resync at commit);
        shrink/failure tears it down for a restart."""
        hosts = self._hosts.current_hosts()
        np = self._cap_np()
        slots = get_host_assignments(hosts, np)
        coord_port = free_port()
        coord_addr = "127.0.0.1" if slots[0].hostname in (
            "localhost", "127.0.0.1") else slots[0].hostname
        self._registry.reset(np)
        # drop listener registrations from the previous generation: its
        # processes are gone, and pushing signed world docs at dead (or
        # recycled) host:port addresses wastes a thread per publish and
        # could hand the doc to an unrelated process. This generation's
        # workers re-register at their first commit.
        self._kv.clear("notify")
        self._hosts_changed.clear()
        gen = self._generation
        self._generation += 1
        get_logger().info("elastic generation %d: np=%d hosts=%s", gen, np,
                          [h.hostname for h in hosts])
        self._publish_world(gen, slots, coord_addr, coord_port)

        failure = threading.Event()
        teardown = threading.Event()  # shrink: kill survivors for restart
        fail_lock = threading.Lock()

        def run_slot(slot, slot_gen):
            extra_env = {
                "HVD_TPU_ELASTIC": "1",
                "HVD_ELASTIC_GENERATION": str(slot_gen),
                "HVD_ELASTIC_CKPT": self._ckpt_dir,
                "HVD_ELASTIC_SECRET": self._world_secret.hex(),
                "HVD_ELASTIC_KV": f"127.0.0.1:{self._kv.port}"
                if slot.hostname in ("localhost", "127.0.0.1")
                else f"{self._driver_addr}:{self._kv.port}"}
            prefix = f"[{slot.rank}]" if self._verbose else ""
            if self._remote_exec is not None:
                # agent transport: ship the RAW worker command + env; the
                # agent on slot.hostname execs it locally (no ssh wrap)
                from horovod_tpu.runner.exec_run import build_worker_env
                wenv = build_worker_env(slot, coord_addr, coord_port,
                                        self._env)
                wenv.update(extra_env)
                if self._preshared_secret is not None:
                    # the caller distributed the secret over its own
                    # trusted channel; keep it off the wire
                    wenv.pop("HVD_ELASTIC_SECRET", None)
                rc = self._remote_exec(slot, self._command, wenv,
                                       [failure, teardown])
            else:
                # local-vs-ssh dispatch shared with the static launcher so
                # multi-host elastic jobs actually place workers remotely
                cmd, env = slot_command(
                    slot, self._command, coord_addr, coord_port, self._env,
                    extra_env=extra_env)
                rc = safe_execute(cmd, env=env, prefix=prefix,
                                  events=[failure, teardown],
                                  timestamp=self._timestamp_output)
            if rc == 0:
                self._registry.record(slot.rank, slot.hostname, SUCCESS)
                return
            # distinguish the originating failure from workers the driver
            # tore down because of it (those must not poison the blacklist)
            with fail_lock:
                torn_down = failure.is_set() or teardown.is_set()
                failure.set()
            self._registry.record(slot.rank, slot.hostname,
                                  TERMINATED if torn_down else FAILURE)

        threads = {}
        for s in slots:
            t = threading.Thread(target=run_slot, args=(s, gen),
                                 daemon=True)
            threads[s.rank] = t
            t.start()
        # the job is DONE when every rank of the generation it started
        # with succeeds — growth-spawned stragglers whose world the
        # survivors never joined (completion raced the scale-up) must not
        # hold the driver hostage
        essential_ranks = [s.rank for s in slots]
        essential_gen = gen  # growth below reuses the name `gen`

        while any(t.is_alive() for t in threads.values()):
            time.sleep(0.25)
            if not failure.is_set() and not teardown.is_set() and \
                    self._registry.count(SUCCESS) >= len(essential_ranks) \
                    and all(not threads[r].is_alive()
                            for r in essential_ranks):
                # survivors finished; kill growth stragglers still waiting
                # for a rendezvous that will never complete
                teardown.set()
            if failure.is_set() or not self._hosts_changed.is_set():
                continue
            # -- membership changed mid-generation -------------------------
            self._hosts_changed.clear()
            new_hosts = self._hosts.current_hosts()
            new_np = self._cap_np()
            old_hostnames = {s.hostname for s in slots}
            still_there = old_hostnames.issubset(
                {h.hostname for h in new_hosts})
            if not still_there or new_np < np:
                # shrink / host lost: restart path
                teardown.set()
                continue
            if new_np <= np:
                continue  # capacity we are not using anyway
            # GROWTH: stable assignment keeps existing ranks; spawn only
            # the new slots, publish the new world for survivor resync
            new_slots = get_host_assignments(new_hosts, new_np)
            if not all(ns.rank == s.rank and ns.hostname == s.hostname
                       for ns, s in zip(new_slots, slots)):
                # assignment reshuffled existing ranks (host reordering):
                # in-place resync would double-assign ranks — restart
                get_logger().warning(
                    "growth reshuffled existing ranks; falling back to a "
                    "generation restart")
                teardown.set()
                continue
            coord_port = free_port()  # fresh rendezvous for the new world
            gen = self._generation
            self._generation += 1
            get_logger().info(
                "elastic generation %d (growth, in-place): np=%d->%d",
                gen, np, new_np)
            self._publish_world(gen, new_slots, coord_addr, coord_port)
            for s in new_slots[np:]:
                t = threading.Thread(target=run_slot, args=(s, gen),
                                     daemon=True)
                threads[s.rank] = t
                t.start()
            slots = new_slots
            np = new_np

        ess_ok = all(
            self._registry.state_of(r) == SUCCESS for r in essential_ranks)
        if ess_ok and self._registry.count(FAILURE) == 0:
            # only the ESSENTIAL ranks are guaranteed complete — in-place
            # growth may have raised np while its stragglers were torn
            # down after the survivors finished in the old world
            self._final_np = len(essential_ranks)
            self._final_gen = essential_gen
            return SUCCESS
        if (teardown.is_set() or self._hosts_changed.is_set()) and \
                self._registry.count(FAILURE) == 0:
            return "HOSTS_CHANGED"
        if self._registry.count(FAILURE) > 0:
            for host, n in self._registry.failed_hosts().items():
                # a host whose every worker failed is blacklisted
                # (reference: driver blacklist, driver.py:297-313)
                host_slots = sum(1 for s in slots if s.hostname == host)
                if n >= host_slots:
                    self._hosts.blacklist(host)
            return FAILURE
        self._final_np = len(essential_ranks)
        self._final_gen = essential_gen
        return SUCCESS

    @property
    def final_np(self) -> Optional[int]:
        """World size of the generation that completed successfully (None
        until then) — callers collecting per-rank artifacts use it to
        ignore leftovers from aborted generations."""
        return getattr(self, "_final_np", None)

    @property
    def final_generation(self) -> Optional[int]:
        """Generation number the completed ranks were launched with
        (their ``HVD_ELASTIC_GENERATION``) — pairs with final_np for
        generation-scoped artifact collection."""
        return getattr(self, "_final_gen", None)

    # -- main loop -----------------------------------------------------------
    def run(self) -> int:
        self._wait_for_min_hosts(timeout=self._start_timeout)
        disc = threading.Thread(target=self._discovery_loop, daemon=True)
        disc.start()
        try:
            while True:
                result = self._run_generation()
                if result == SUCCESS:
                    return 0
                if self._registry.reset_limit_reached():
                    get_logger().error(
                        "elastic reset limit reached after %d generations",
                        self._registry.reset_count)
                    return 1
                # wait until we have enough usable slots again
                try:
                    self._wait_for_min_hosts(timeout=self._elastic_timeout)
                except TimeoutError:
                    return 1
        finally:
            self._stop.set()
            disc.join(timeout=3)
            self._kv.stop()


def run_elastic(discovery: HostDiscovery, np: Optional[int],
                command: List[str],
                min_np: int = 1, max_np: Optional[int] = None,
                env: Optional[Dict[str, str]] = None,
                verbose: bool = False,
                reset_limit: Optional[int] = None,
                timestamp_output: bool = False,
                start_timeout: Optional[float] = None,
                elastic_timeout: Optional[float] = None) -> int:
    driver = ElasticDriver(discovery, command, min_np=min_np, max_np=max_np,
                           env=env, verbose=verbose, reset_limit=reset_limit,
                           target_np=np, timestamp_output=timestamp_output,
                           start_timeout=start_timeout,
                           elastic_timeout=elastic_timeout)
    return driver.run()
